"""Cost-model-driven solver selection.

Reference: nodes/learning/CostModel.scala:6-16, LeastSquaresEstimator.scala:26-87,
ChainUtils.scala (TransformerLabelEstimatorChain).

The analytic cost(n, d, k, sparsity, numMachines, cpuW, memW, netW) models
keep the reference's feature extractors verbatim; `numMachines` maps to mesh
device count. The ACTIVE weights are TPU-derived (fit from measured on-chip
DEVICE time at the bench geometries — see the derivation at TPU_CPU_WEIGHT
below and ``scripts/fit_cost_weights.py``), matching the reference's defining
discipline of weights fit on the machine they steer
(LeastSquaresEstimator.scala:17,28-31). ``KEYSTONE_COST_WEIGHTS=ec2``
restores the reference's cluster constants (cpu=3.8e-4, mem=2.9e-1,
net=1.32 — 16-node r3.4xlarge).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from keystone_tpu import obs
from keystone_tpu.data import Dataset
from keystone_tpu.ops.sparse import Densify, Sparsify, is_sparse_dataset
from keystone_tpu.placement.engine import (
    KIND_IMAGE_TIER,
    KIND_MESH,
    KIND_SOLVER,
    PlacementEngine,
)
from keystone_tpu.workflow import LabelEstimator, Transformer
from keystone_tpu.workflow.optimizable import OptimizableLabelEstimator

logger = logging.getLogger("keystone_tpu.cost")

# Reference cluster cost weights (LeastSquaresEstimator.scala:28-31; fit on
# a 2015 16-node r3.4xlarge cluster). Selectable via KEYSTONE_COST_WEIGHTS=
# ec2 — for A/B against the reference's selection behavior, and for tests
# that pin the reference weight set.
EC2_CPU_WEIGHT = 3.8e-4
EC2_MEM_WEIGHT = 2.9e-1
EC2_NETWORK_WEIGHT = 1.32
EC2_SPARSE_GATHER_OVERHEAD = 8.0
# Pre-round-6 aliases (these were the active defaults then).
DEFAULT_CPU_WEIGHT = EC2_CPU_WEIGHT
DEFAULT_MEM_WEIGHT = EC2_MEM_WEIGHT
DEFAULT_NETWORK_WEIGHT = EC2_NETWORK_WEIGHT

# Fallback device-memory budget when the backend reports no memory stats
# (CPU test meshes); real chips report bytes_limit (v5e: ~15.75 GB).
DEFAULT_HBM_BYTES = 16 << 30
# Fraction of device memory a solver's resident operands may claim: the
# rest covers XLA scratch, fusion temporaries and transfer buffers.
DEFAULT_HBM_UTILIZATION = 0.85
# Fallback HOST-memory budget when the OS reports nothing. The host tier
# sits between HBM and disk: candidates needing the dataset host-resident
# are infeasible past it, and the shard-backed streaming (disk) tier —
# which stages only prefetch-depth segments — becomes the only door.
DEFAULT_HOST_BYTES = 64 << 30
# Fraction of host RAM the dataset may claim (the rest covers the
# process, staging buffers, page cache churn).
DEFAULT_HOST_UTILIZATION = 0.8


def device_memory_bytes() -> int:
    """Per-device memory budget: the backend's reported limit, else the
    conservative default."""
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            return int(limit)
    except Exception:  # backends without memory stats
        pass
    return DEFAULT_HBM_BYTES


def host_memory_bytes() -> int:
    """Host-RAM budget for resident datasets: the
    ``KEYSTONE_HOST_BUDGET_BYTES`` env override (the ops knob — and the
    test hook forcing the disk tier), else the OS-reported physical
    memory, else the conservative default."""
    import os

    env = os.environ.get("KEYSTONE_HOST_BUDGET_BYTES")
    if env:
        return int(float(env))
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
        if pages > 0 and page > 0:
            return int(pages * page)
    except (ValueError, OSError, AttributeError):
        pass
    return DEFAULT_HOST_BYTES

# TPU weights — ACTIVE by default. Fit from measured on-chip DEVICE time
# (not wall: the tunnel's ~0.1 s dispatch overhead and host transfer are
# excluded — the round-5 fit's failure mode) at the BENCH_r05 geometries,
# under the max(cpu·flops, mem·bytes) form the selector evaluates:
#
#   cpu = 3.8e-15 s per model-flop unit. The two MXU-bound rows bracket it:
#     the resident block row (0.327 s device = 3 sweeps of n·d·(bs+k) at
#     n=262144, d=16384 → 5.98e-15) and the streamed full-n headline
#     (4.107 s device = 2.0 × n·d·(d+k) at n=2.2e6 → 3.45e-15); the
#     geometric middle reproduces both within ~30%.
#   mem = 1.9e-11 s per sequentially-scanned f32 cell (≈ 210 GB/s achieved
#     streaming — below the 819 GB/s pin-rate peak because the models count
#     one scan of n·d while the folds re-read tiles). Chosen jointly with
#     cpu so that every MEASURED pairwise ordering reproduces: resident
#     block < streamed at in-budget geometries, block < 20-iteration dense
#     LBFGS, sparse gram < sparse gather (tests/test_cost_replay.py).
#   net = 1.0e-11 s per float (~100 G f32/s over ICI) — PINNED, not fit: a
#     single-chip measurement cannot observe the network term; refit on a
#     real multi-chip mesh before trusting cross-mesh rankings.
#
# The sparse gather path's random-access rate (measured 2.1e8 cells/s on
# the amazon row — 7.903 s / 20 iters / 2 passes / 4.15e7 active cells) is
# ~900x the sequential mem rate; it lives in the SparseLBFGS model's
# sparse_overhead factor, refit to 500 from the same row (the gram engine's
# prediction then lands at 1.78 s vs 1.805 measured). Re-derive all of
# these with ``python scripts/fit_cost_weights.py`` on-chip.
TPU_CPU_WEIGHT = 3.8e-15
TPU_MEM_WEIGHT = 1.9e-11
TPU_NETWORK_WEIGHT = 1.0e-11  # pinned (single-chip unobservable), not fit
TPU_SPARSE_GATHER_OVERHEAD = 500.0

# Sketched-engine weight families (ISSUE 17). Like the gather overhead,
# each is a random-access multiplier on the sequential mem rate for the
# engine's signature pass, refit from traces by ``bin/calibrate --refit``:
#
#   srht_sketch_overhead — the SRHT engine's densify scatter (writing
#     n·d·s active cells into chunk slabs before the FFT mixing). Seeded
#     slightly above the gather overhead: a scatter WRITE pays
#     read-modify-write per cell where the gather pass's read does not.
#   countsketch_overhead — the IHS engine's O(nnz) CountSketch
#     scatter-add into the flattened (m·d) accumulator. Cheaper than the
#     densify: one add per stored cell, no slab zero-fill, bucket
#     locality within a chunk.
#
# The EC2 values keep the reference-cluster convention (mem already at
# cluster rates, so the factors stay single-digit).
TPU_SRHT_SKETCH_OVERHEAD = 650.0
TPU_COUNTSKETCH_OVERHEAD = 250.0
EC2_SRHT_SKETCH_OVERHEAD = 10.0
EC2_COUNTSKETCH_OVERHEAD = 6.0

# Image-tier decode multiplier (ISSUE 18): host-side decompression of
# one encoded image into f32 cells, as a multiplier on the sequential
# mem rate per DECODED cell. Seeded from the native PNM decoder's
# ~1 GB/s single-thread throughput (≈ 4e-9 s per f32 cell against the
# 1.9e-11 s sequential rate); the EC2 value keeps the reference
# cluster's convention of single-digit factors. Refit from traces like
# the other per-engine overheads.
TPU_IMAGE_DECODE_OVERHEAD = 200.0
EC2_IMAGE_DECODE_OVERHEAD = 4.0

# Zoo page-in multiplier (ISSUE 19): host-side decode + CRC + pytree
# rebuild of one evicted tenant's spill, as a multiplier on the
# sequential mem rate per resident BYTE. Seeded from the spill codec's
# ~1 GB/s single-thread restore (1/(1.9e-11 x 50) ≈ 1 GB/s); the EC2
# value keeps the cluster convention of single-digit factors. This is
# the weight family behind ``PlacementEngine.price_page_in`` — the
# ModelZoo seeds its page-in EMA from it instead of a hardcoded
# constant, so ``bin/calibrate --refit`` covers zoo paging like every
# other engine overhead.
TPU_ZOO_PAGE_OVERHEAD = 50.0
EC2_ZOO_PAGE_OVERHEAD = 2.0


# Weight-family spec for trace-calibrated constants:
# KEYSTONE_COST_WEIGHTS=calibrated:<path> points at a refit artifact
# written by the calibration plane (obs/calibrate.py — trace-driven
# refit with provenance: source run_ids, span counts, residuals).
CALIBRATED_PREFIX = "calibrated:"

# Loaded-artifact cache keyed by path -> (mtime, weights dict): a
# selector consulting the env per construction must not re-read and
# re-validate the JSON every time, but a refreshed artifact (refit in
# place) must be picked up.
_CALIBRATED_CACHE: dict = {}


def _calibrated_weights(path: str) -> dict:
    import os

    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError as e:
        raise ValueError(
            f"KEYSTONE_COST_WEIGHTS={CALIBRATED_PREFIX}{path}: artifact "
            f"is unreadable: {e}"
        ) from e
    cached = _CALIBRATED_CACHE.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    from keystone_tpu.obs.calibrate import load_calibration_artifact

    weights = dict(load_calibration_artifact(path)["weights"])
    _CALIBRATED_CACHE[path] = (mtime, weights)
    return weights


def _parse_weights_env() -> Tuple[str, Optional[str]]:
    """Parse ``KEYSTONE_COST_WEIGHTS`` into (family, artifact_path).

    Accepted (family part case-insensitive; artifact paths keep their
    case): unset/empty or ``tpu`` -> the TPU constants, ``ec2`` -> the
    reference cluster set, ``calibrated:<path>`` -> a refit artifact.
    Anything else raises naming the variable — a typo'd family must not
    silently select the default and mis-price every decision (the exact
    failure mode the calibration plane exists to catch)."""
    import os

    raw = os.environ.get("KEYSTONE_COST_WEIGHTS", "").strip()
    low = raw.lower()
    if not raw or low == "tpu":
        return "tpu", None
    if low == "ec2":
        return "ec2", None
    if low.startswith(CALIBRATED_PREFIX):
        return "calibrated", raw[len(CALIBRATED_PREFIX):]
    raise ValueError(
        f"KEYSTONE_COST_WEIGHTS={raw!r}: expected 'tpu', 'ec2' or "
        f"'calibrated:<artifact.json>'"
    )


def weights_family_name() -> str:
    """The active weight family's name: ``tpu`` (default), ``ec2``, or
    ``calibrated`` — what decision audits and calibration reports record
    as provenance."""
    return _parse_weights_env()[0]


def active_weights() -> Tuple[float, float, float]:
    """The selector's (cpu, mem, network) weights: TPU-derived by
    default; ``KEYSTONE_COST_WEIGHTS=ec2`` restores the reference's
    cluster constants; ``KEYSTONE_COST_WEIGHTS=calibrated:<path>`` loads
    a trace-refit artifact (obs/calibrate.py) — malformed or missing
    artifacts, and unknown family names, raise naming the variable
    rather than mis-pricing silently."""
    family, path = _parse_weights_env()
    if family == "ec2":
        return EC2_CPU_WEIGHT, EC2_MEM_WEIGHT, EC2_NETWORK_WEIGHT
    if family == "calibrated":
        w = _calibrated_weights(path)
        return float(w["cpu"]), float(w["mem"]), float(w["network"])
    return TPU_CPU_WEIGHT, TPU_MEM_WEIGHT, TPU_NETWORK_WEIGHT


def sparse_gather_overhead() -> float:
    """Random-access multiplier for the sparse gather engine's mem term,
    matching the active weight family (the EC2 mem weight already prices
    bytes at cluster rates, so its historical factor stays 8). A
    calibrated artifact fit from traces with no gather rows records
    null — the TPU constant stands in, since the artifact's (cpu, mem)
    are TPU-fit refinements."""
    family, path = _parse_weights_env()
    if family == "ec2":
        return EC2_SPARSE_GATHER_OVERHEAD
    if family == "calibrated":
        so = _calibrated_weights(path).get("sparse_gather_overhead")
        return float(so) if so is not None else TPU_SPARSE_GATHER_OVERHEAD
    return TPU_SPARSE_GATHER_OVERHEAD


def srht_sketch_overhead() -> float:
    """Random-access multiplier for the SRHT engine's densify-scatter
    sketch pass, per the active weight family. Same null convention as
    :func:`sparse_gather_overhead`: a calibrated artifact fit from
    traces with no SRHT rows records null and the TPU constant stands
    in."""
    family, path = _parse_weights_env()
    if family == "ec2":
        return EC2_SRHT_SKETCH_OVERHEAD
    if family == "calibrated":
        so = _calibrated_weights(path).get("srht_sketch_overhead")
        return float(so) if so is not None else TPU_SRHT_SKETCH_OVERHEAD
    return TPU_SRHT_SKETCH_OVERHEAD


def countsketch_overhead() -> float:
    """Random-access multiplier for the IHS engine's CountSketch
    scatter-add pass, per the active weight family (null-in-artifact
    falls back to the TPU constant, as above)."""
    family, path = _parse_weights_env()
    if family == "ec2":
        return EC2_COUNTSKETCH_OVERHEAD
    if family == "calibrated":
        so = _calibrated_weights(path).get("countsketch_overhead")
        return float(so) if so is not None else TPU_COUNTSKETCH_OVERHEAD
    return TPU_COUNTSKETCH_OVERHEAD


def image_decode_overhead() -> float:
    """Random-access multiplier for the image tier's host decode pass,
    per the active weight family (null-in-artifact falls back to the TPU
    constant, as above)."""
    family, path = _parse_weights_env()
    if family == "ec2":
        return EC2_IMAGE_DECODE_OVERHEAD
    if family == "calibrated":
        so = _calibrated_weights(path).get("image_decode_overhead")
        return float(so) if so is not None else TPU_IMAGE_DECODE_OVERHEAD
    return TPU_IMAGE_DECODE_OVERHEAD


def zoo_page_overhead() -> float:
    """Random-access multiplier for the zoo's tenant page-in pass
    (spill decode + CRC + pytree rebuild), per the active weight family
    (null-in-artifact falls back to the TPU constant, as above)."""
    family, path = _parse_weights_env()
    if family == "ec2":
        return EC2_ZOO_PAGE_OVERHEAD
    if family == "calibrated":
        so = _calibrated_weights(path).get("zoo_page_overhead")
        return float(so) if so is not None else TPU_ZOO_PAGE_OVERHEAD
    return TPU_ZOO_PAGE_OVERHEAD


def candidate_label(est) -> str:
    """Stable human-readable label of one solver candidate — the name a
    :class:`~keystone_tpu.obs.tracer.CostDecision` event records and the
    replay tests assert against. Disambiguates the engine/storage-class
    variants of one estimator type (``solver=``/``compress=``)."""
    name = type(est).__name__
    qual = [
        str(v) for v in (
            getattr(est, "solver", None), getattr(est, "compress", None)
        ) if v
    ]
    return name + (f"[{','.join(qual)}]" if qual else "")


# ---------------------------------------------------------------------------
# Mesh-layout pricing (ISSUE 16): layouts are first-class candidates
# ---------------------------------------------------------------------------

# Candidate (data, model) mesh shapes the layout selector prices for a
# data-parallel streamed gram fold. 1x1 is the single-chip baseline the
# BENCH rows measured; 8x1 puts every device on the fold's row axis; 4x2
# spends half the pod replicating along the model axis (which the gram
# fold cannot use — it prices as a 4-way fold plus replica broadcast).
MESH_LAYOUTS: Tuple[Tuple[int, int], ...] = ((1, 1), (4, 1), (4, 2), (8, 1))


def mesh_layout_label(data: int, model: int) -> str:
    """Stable candidate label of one mesh layout — what the
    ``mesh_layout`` CostDecision records and the replay test pins."""
    return f"mesh[data={int(data)},model={int(model)}]"


def price_mesh_layout(
    n: int, d: int, k: int, data: int, model: int,
    *,
    nnz_per_row: Optional[int] = None,
    cpu_weight: Optional[float] = None,
    mem_weight: Optional[float] = None,
    network_weight: Optional[float] = None,
) -> float:
    """Predicted seconds for ONE streamed gram fit on a (data × model)
    mesh.

    The model mirrors the fold's actual program shape
    (ops/learning/lbfgs.py ``_run_lbfgs_gram_streamed_mesh``):

    - each of the ``data`` devices folds its contiguous row shard locally
      (compute and scan terms divide by ``data`` and by nothing else —
      the gram fold has no model-axis parallelism);
    - ONE ring all-reduce of (G upper-tri, AtY, yty) crosses the ICI per
      fit: ``2·(p-1)/p`` of the reduced floats move per device;
    - model-axis replicas fold identical shards, so ``model > 1`` buys
      nothing and pays the operand broadcast to each extra replica.
    """
    if cpu_weight is None or mem_weight is None or network_weight is None:
        aw = active_weights()
        cpu_weight = cpu_weight if cpu_weight is not None else aw[0]
        mem_weight = mem_weight if mem_weight is not None else aw[1]
        network_weight = network_weight if network_weight is not None else aw[2]
    p, q = int(data), int(model)
    active = float(nnz_per_row) if nnz_per_row else float(d)
    # Per-fit work: gram outer products (active² MACs/row) + AtY + labels.
    flops = 2.0 * n * active * (active + k)
    cells = float(n) * (2.0 * active + k)  # idx+val lanes and the labels
    fold_s = max(cpu_weight * flops, mem_weight * cells) / p
    # The single psum tree-reduction per fit (upper-tri G + AtY + yty).
    reduce_floats = d * (d + 1) / 2.0 + d * k + 1.0
    net_s = (
        network_weight * reduce_floats * 2.0 * (p - 1) / p if p > 1 else 0.0
    )
    # Replica tax: the fold operands reach each model-axis replica over
    # the same interconnect the psum rides.
    net_s += network_weight * (cells / p) * (q - 1)
    return fold_s + net_s


def mesh_layout_resident_bytes(
    n: int, d: int, k: int, data: int,
    nnz_per_row: Optional[int] = None,
) -> float:
    """Per-device HBM claim of a chip-resident row shard under a layout:
    compressed-COO lanes (int16 idx + bf16 val = 4 B/nnz) when the input
    is sparse, f32 rows otherwise, plus the f32 label shard."""
    row = (
        COMPRESSED_BYTES_PER_NNZ_DEFAULT * float(nnz_per_row)
        if nnz_per_row else 4.0 * d
    )
    return (n / max(int(data), 1)) * (row + 4.0 * k)


# Kept here (not imported from data/resident.py) so pricing has no
# data-plane import cycle; tests/test_cost_replay.py asserts the two
# constants agree.
COMPRESSED_BYTES_PER_NNZ_DEFAULT = 4.0


def choose_mesh_layout(
    n: int, d: int, k: int,
    *,
    nnz_per_row: Optional[int] = None,
    layouts: Sequence[Tuple[int, int]] = MESH_LAYOUTS,
    num_devices: Optional[int] = None,
    hbm_bytes: Optional[int] = None,
    hbm_utilization: float = DEFAULT_HBM_UTILIZATION,
):
    """Select a mesh layout for a streamed gram fit, with the decision
    recorded as first-class ``cost.decision`` evidence.

    Prices every candidate layout in ``layouts`` (default
    :data:`MESH_LAYOUTS`), marks infeasible the ones needing more chips
    than ``num_devices`` (default: the runtime's device count), and
    emits a ``decision="mesh_layout"`` CostDecision whose
    :class:`~keystone_tpu.obs.tracer.CostOutcomeRef` the runner stamps
    with the measured fit wall — ``bin/calibrate`` joins these records
    exactly like solver decisions (obs/calibrate.py
    ``CALIBRATED_DECISIONS``).

    Returns ``((data, model), outcome_ref)``; ``outcome_ref`` is None
    when no tracer is active.
    """
    devices = int(num_devices) if num_devices else max(len(jax.devices()), 1)
    budget = (
        hbm_bytes if hbm_bytes is not None else device_memory_bytes()
    ) * hbm_utilization
    cpu_w, mem_w, net_w = active_weights()
    try:
        family = weights_family_name()
    except ValueError:
        family = "custom"

    def feasible(p: int, q: int) -> bool:
        return p * q <= devices

    costs = [
        price_mesh_layout(
            n, d, k, p, q, nnz_per_row=nnz_per_row,
            cpu_weight=cpu_w, mem_weight=mem_w, network_weight=net_w,
        ) if feasible(p, q) else float("inf")
        for p, q in layouts
    ]
    if all(c == float("inf") for c in costs):
        raise ValueError(
            f"no candidate mesh layout fits {devices} device(s): "
            f"{[mesh_layout_label(p, q) for p, q in layouts]}"
        )
    candidates = [
        {
            "label": mesh_layout_label(p, q),
            "cost_s": (None if c == float("inf") else float(c)),
            "feasible": c != float("inf"),
            "resident_bytes": float(
                mesh_layout_resident_bytes(n, d, k, p, nnz_per_row)
            ),
            "chip_resident": (
                mesh_layout_resident_bytes(n, d, k, p, nnz_per_row)
                <= budget
            ),
            "host_ok": True,
        }
        for (p, q), c in zip(layouts, costs)
    ]
    # The unified placement stream rides alongside the legacy
    # cost.decision record; the engine's first-minimum argmin IS
    # np.argmin, so the recorded winner is unchanged by construction.
    choice = PlacementEngine(weights_family=family).decide(
        KIND_MESH, candidates,
        context={
            "n": int(n), "d": int(d), "k": int(k),
            "machines": devices,
            "hbm_budget_bytes": float(budget),
        },
    )
    winner = layouts[choice.index]
    ref = obs.record_cost_decision(obs.CostDecision(
        decision="mesh_layout",
        winner=mesh_layout_label(*winner),
        candidates=candidates,
        reason="argmin",
        context={
            "n": int(n), "d": int(d), "k": int(k),
            "sparsity": (
                float(nnz_per_row) / d if nnz_per_row else 1.0
            ),
            "machines": devices,
            "hbm_budget_bytes": float(budget),
            "nnz_per_row": (
                int(nnz_per_row) if nnz_per_row else None
            ),
            "weights": {
                "cpu": cpu_w, "mem": mem_w, "network": net_w,
                "family": family,
            },
        },
    ))
    return winner, ref


IMAGE_TIERS = ("resident", "resident_u8", "disk_shards")


def choose_image_tier(
    n_images: int, d: int, k: int,
    *,
    images_per_segment: int = 256,
    prefetch_depth: int = 2,
    host_budget_bytes: Optional[float] = None,
    host_utilization: float = 0.8,
):
    """Select the storage tier for a decoded image set, recorded as
    first-class ``cost.decision`` evidence — this is what lets
    ``Pipeline.fit`` route a past-host-RAM image set through disk shards
    with NO flag: the loader prices the tiers and the infeasible ones
    price to inf.

    ``d`` is decoded floats per image (x·y·c after augmentation), ``k``
    the label width. Candidates:

      - ``resident``: decoded f32 rows held in host RAM — one decode
        pass, cheapest reads, infeasible past the host budget.
      - ``resident_u8``: the compressed-resident tier — uint8 pixel rows
        (exact for 8-bit sources), 4× smaller residency, a cast per
        epoch on the way to the device.
      - ``disk_shards``: spill through ``DiskDenseShardWriter`` — host
        residency is ``(prefetch_depth + 1)`` staged segments only,
        always feasible; pays the spill write + re-read traffic.

    Returns ``(tier_name, outcome_ref)``; ``outcome_ref`` is None when
    no tracer is active.
    """
    cpu_w, mem_w, net_w = active_weights()
    try:
        family = weights_family_name()
    except ValueError:
        family = "custom"
    if host_budget_bytes is not None:
        budget = float(host_budget_bytes)
    else:
        budget = host_memory_bytes() * host_utilization

    n = int(n_images)
    cells = float(n) * (d + k)
    decode_s = mem_w * image_decode_overhead() * float(n) * d
    seg_bytes = float(images_per_segment) * (4.0 * d + 4.0 * k)
    resident_bytes = {
        "resident": cells * 4.0,
        "resident_u8": float(n) * (d + 4.0 * k),
        "disk_shards": (prefetch_depth + 1) * seg_bytes,
    }
    tier_cost = {
        # One decode pass each; reads price the per-epoch traffic.
        "resident": decode_s + mem_w * cells,
        # u8 rows read 1/4 the bytes but pay a widening cast per epoch.
        "resident_u8": decode_s + mem_w * cells * 1.25,
        # Spill write + shard re-read (checksummed), both full passes.
        "disk_shards": decode_s + mem_w * cells * 3.0,
    }
    costs = {
        t: (tier_cost[t] if resident_bytes[t] <= budget else float("inf"))
        for t in IMAGE_TIERS
    }
    if all(c == float("inf") for c in costs.values()):
        raise ValueError(
            f"no image tier fits the host budget {budget:.3g} B "
            f"(even {prefetch_depth + 1} staged segments of "
            f"{seg_bytes:.3g} B); shrink images_per_segment"
        )
    candidates = [
        {
            "label": t,
            "cost_s": (None if costs[t] == float("inf") else float(costs[t])),
            "feasible": costs[t] != float("inf"),
            "resident_bytes": float(resident_bytes[t]),
            "chip_resident": False,  # the image tier is host-side
            "host_ok": resident_bytes[t] <= budget,
        }
        for t in IMAGE_TIERS
    ]
    # Placement mirror: min-over-tuple-order equals the engine's
    # first-minimum over the candidate list (both first on ties).
    choice = PlacementEngine(weights_family=family).decide(
        KIND_IMAGE_TIER, candidates,
        context={
            "n": n, "d": int(d), "k": int(k),
            "images_per_segment": int(images_per_segment),
            "prefetch_depth": int(prefetch_depth),
            "host_budget_bytes": float(budget),
        },
    )
    winner = IMAGE_TIERS[choice.index]
    ref = obs.record_cost_decision(obs.CostDecision(
        decision="image_tier",
        winner=winner,
        candidates=candidates,
        reason="argmin",
        context={
            "n": n, "d": int(d), "k": int(k),
            "images_per_segment": int(images_per_segment),
            "prefetch_depth": int(prefetch_depth),
            "host_budget_bytes": float(budget),
            "weights": {
                "cpu": cpu_w, "mem": mem_w, "network": net_w,
                "family": family,
            },
        },
    ))
    return winner, ref


class CostModel:
    """Analytic per-solver performance model (CostModel.scala:6-16)."""

    def cost(
        self,
        n: int,
        d: int,
        k: int,
        sparsity: float,
        num_machines: int,
        cpu_weight: float,
        mem_weight: float,
        network_weight: float,
    ) -> float:
        raise NotImplementedError


class TransformerLabelEstimatorChain(LabelEstimator):
    """Fuse a Transformer with a LabelEstimator into one LabelEstimator
    (reference: ChainUtils.scala)."""

    def __init__(self, transformer: Transformer, estimator: LabelEstimator):
        self.transformer = transformer
        self.estimator = estimator

    def fit(self, data: Dataset, labels: Dataset):
        transformed = self.transformer.batch_apply(data)
        inner = self.estimator.fit(transformed, labels)

        chain_transformer = self.transformer

        class Chained(Transformer):
            def apply(self, x):
                return inner.apply(chain_transformer.apply(x))

            def batch_apply(self, ds: Dataset) -> Dataset:
                return inner.batch_apply(chain_transformer.batch_apply(ds))

        return Chained()

    @property
    def weight(self) -> int:
        return getattr(self.estimator, "weight", 1)


class LeastSquaresEstimator(OptimizableLabelEstimator):
    """Auto-selecting least-squares solver (LeastSquaresEstimator.scala:26-87).

    Candidates: DenseLBFGS, Sparsify->SparseLBFGS (gather, gram, and
    compressed-resident gram — the int16+bf16 4 B/nnz storage class of
    ``data/resident.py``), Densify->BlockLS(1000, 3),
    Densify->Exact normal equations, the STREAMING tier
    (StreamingLeastSquaresChoice — featurize-inside-the-fit, bound to the
    upstream featurizer by the optimizer's StreamedFitFusionRule), and
    (only when ``allow_approximate``) the randomized tier:
    Densify->SketchedLeastSquaresEstimator (dense CountSketch +
    Hessian-sketch refinement), Sparsify->SketchedLeastSquares (SRHT
    sketch-and-precondition — exact up to CG tolerance) and
    Sparsify->IterativeHessianSketch (input-sparsity-time CountSketch
    folds, ``ops/learning/sketch.py``). ``optimize`` measures
    (n, d, k, sparsity, num devices) from
    the sample and picks the cost-model argmin among candidates whose
    RESIDENT operands fit the device-memory budget — a capacity term the
    reference's cluster cost model (CostModel.scala:6-16) folds into its
    memory weight, and which on a fixed-HBM chip must instead be a hard
    feasibility cut: past it, the streaming tier is the only candidate
    that can run at all.

    The cut prices THREE tiers separately: HBM (per-candidate
    resident_bytes vs the device budget), host RAM (the raw dataset +
    labels vs ``host_budget_bytes`` — every candidate except the disk
    tier needs the dataset host-resident to begin), and DISK (a
    shard-backed input lets the streaming choice stage only
    prefetch-depth segments, so datasets past the host budget route
    through disk shards with no flag — docs/data.md).
    """

    def __init__(
        self,
        lam: float = 0.0,
        num_machines: Optional[int] = None,
        cpu_weight: Optional[float] = None,
        mem_weight: Optional[float] = None,
        network_weight: Optional[float] = None,
        allow_approximate: bool = False,
        hbm_bytes: Optional[float] = None,
        hbm_utilization: float = DEFAULT_HBM_UTILIZATION,
        host_budget_bytes: Optional[float] = None,
        host_utilization: float = DEFAULT_HOST_UTILIZATION,
        block_size: int = 1000,
        block_iters: int = 3,
    ):
        from keystone_tpu.ops.learning.block import BlockLeastSquaresEstimator
        from keystone_tpu.ops.learning.lbfgs import DenseLBFGSwithL2, SparseLBFGSwithL2
        from keystone_tpu.ops.learning.linear import (
            LinearMapEstimator,
            SketchedLeastSquaresEstimator,
        )
        from keystone_tpu.ops.learning.streaming_ls import (
            StreamingLeastSquaresChoice,
        )

        self.lam = lam
        self.num_machines = num_machines
        # None -> the active weight family (TPU-derived by default;
        # KEYSTONE_COST_WEIGHTS=ec2 restores the reference constants).
        # Resolved at construction so one estimator's ranking is stable
        # even if the env flag changes mid-process.
        a_cpu, a_mem, a_net = active_weights()
        self.cpu_weight = a_cpu if cpu_weight is None else cpu_weight
        self.mem_weight = a_mem if mem_weight is None else mem_weight
        self.network_weight = a_net if network_weight is None else network_weight
        self.hbm_bytes = hbm_bytes
        self.hbm_utilization = hbm_utilization
        self.host_budget_bytes = host_budget_bytes
        self.host_utilization = host_utilization

        dense_lbfgs = DenseLBFGSwithL2(lam=lam, num_iterations=20)
        sparse_lbfgs = SparseLBFGSwithL2(lam=lam, num_iterations=20)
        # The gram engine: fold G once on the MXU, iterate data-free —
        # cheaper than gather past ~5 iterations whenever its (d_pad)^2
        # Gramian fits the budget (its resident_bytes carries that term).
        sparse_gram = SparseLBFGSwithL2(
            lam=lam, num_iterations=20, solver="gram"
        )
        # The compressed-resident storage class (data/resident.py,
        # ISSUE 8): the SAME gram iterates over int16+bf16 operands at
        # 4 B/nnz — half the raw COO's residency, feasible only while
        # every index (intercept lane included) fits int16. Priced as a
        # third tier between HBM-raw and disk: identical cost model
        # (the fold runs the same bf16 slabs), so selection is driven
        # by the capacity cut — raw-infeasible, compressed-feasible
        # working sets stay chip-resident instead of streaming, with no
        # flag (tests/test_cost_replay.py replays the Amazon n=30e6
        # geometry).
        sparse_gram_compressed = SparseLBFGSwithL2(
            lam=lam, num_iterations=20, solver="gram",
            compress="int16_bf16",
        )
        block = BlockLeastSquaresEstimator(block_size, block_iters, lam=lam)
        exact = LinearMapEstimator(lam)
        streaming = StreamingLeastSquaresChoice(
            num_iter=block_iters, lam=lam,
            block_size_hint=max(block_size, 1024),
        )
        self._streaming_choice = streaming

        self.options: Sequence[Tuple[object, LabelEstimator]] = [
            (dense_lbfgs, dense_lbfgs),
            (sparse_lbfgs, TransformerLabelEstimatorChain(Sparsify(), sparse_lbfgs)),
            (sparse_gram, TransformerLabelEstimatorChain(Sparsify(), sparse_gram)),
            # Listed AFTER the raw gram engine: equal cost when both fit
            # (argmin takes the first), so compression only engages when
            # raw residency is the binding constraint.
            (sparse_gram_compressed,
             TransformerLabelEstimatorChain(Sparsify(), sparse_gram_compressed)),
            (block, TransformerLabelEstimatorChain(Densify(), block)),
            (exact, TransformerLabelEstimatorChain(Densify(), exact)),
            # The streaming choice is its own graph operator (no Densify
            # chain): StreamedFitFusionRule must see it directly to bind
            # the upstream featurizer; its fit densifies sparse input
            # itself on the resident fallback path.
            (streaming, streaming),
        ]
        if allow_approximate:
            # Beyond the reference's candidate set: randomized sketch-and-
            # solve with Hessian-sketch refinement — cheapest in the tall-
            # and-wide dense regime, but its answer is approximate, so users
            # must opt in.
            sketched = SketchedLeastSquaresEstimator(lam=lam)
            # The streamed sketched tier (ISSUE 17): SRHT sketch-and-
            # precondition and input-sparsity-time IHS over the SAME
            # padded-COO chunk stream the gram fold consumes. Each has its
            # own calibrated weight family (srht_sketch_overhead /
            # countsketch_overhead), so a refit can re-rank them without
            # touching the exact engines' weights.
            from keystone_tpu.ops.learning.sketch import (
                IterativeHessianSketch, SketchedLeastSquares,
            )

            srht = SketchedLeastSquares(lam=lam)
            ihs = IterativeHessianSketch(lam=lam)
            self.options = list(self.options) + [
                (sketched, TransformerLabelEstimatorChain(Densify(), sketched)),
                (srht, TransformerLabelEstimatorChain(Sparsify(), srht)),
                (ihs, TransformerLabelEstimatorChain(Sparsify(), ihs)),
            ]
        self._default = dense_lbfgs

    @property
    def default(self) -> LabelEstimator:
        return self._default

    @property
    def weight(self) -> int:
        return self._default.weight

    def optimize(self, sample: Dataset, labels_sample: Dataset):
        # total_n: the full dataset size attached by the sample collector;
        # sample.n is just the handful of sampled rows.
        n = getattr(sample, "total_n", sample.n)
        if is_sparse_dataset(sample):
            indices = np.asarray(sample.data["indices"])
            # Feature width: prefer the TRUE width threaded through by the
            # sample collector (``total_d`` — declared by the vectorizer or
            # measured over the full index array); ``indices.max()+1`` over
            # a 24-row sample undershoots whenever the sample misses the
            # top ids, mis-pricing every sparse candidate's resident_bytes.
            measured_d = int(indices.max()) + 1
            d = max(int(getattr(sample, "total_d", 0) or 0), measured_d)
            # Active fraction measured over the SAMPLE's valid rows
            # (dividing by the full n would collapse sparsity toward zero
            # whenever the collector attaches total_n; padded-COO rows
            # hold -1 lanes, which the >= 0 mask already excludes).
            sparsity = float(
                (indices >= 0).sum() / (max(sample.n, 1) * d)
            )
        elif sample.is_host:
            first = sample.to_list()[0]
            d = int(np.asarray(first).shape[-1])
            X = np.stack([np.asarray(x) for x in sample.to_list()])
            sparsity = float((X != 0).mean())
        else:
            d = int(np.asarray(sample.array).shape[-1])
            # Slice by the sample's VALID rows, matching the sparse branch:
            # n here is the full-dataset size, so ``[: n]`` would keep any
            # zero-padded tail rows and deflate the measured sparsity.
            sparsity = float(
                np.mean(np.asarray(sample.array[: sample.n]) != 0)
            )
        k = int(np.asarray(labels_sample.array).shape[-1])
        machines = self.num_machines or max(len(jax.devices()), 1)

        # Raw-source row bytes (attached by the sample collector): the
        # streaming tier keeps RAW rows resident, not features. The
        # density flag lets its capacity model default an UNSET raw width
        # honestly — a dense row is the full 4d bytes, not a capped guess.
        raw_row_bytes = getattr(sample, "source_row_bytes", None)
        self._streaming_choice.raw_row_bytes = raw_row_bytes
        self._streaming_choice.input_is_sparse = is_sparse_dataset(sample)
        # DISK tier: a shard-backed source streams raw rows from disk
        # segments — the streaming choice's resident operand stops
        # scaling with n, and host-RAM feasibility is priced per
        # candidate below.
        shard_backed = bool(getattr(sample, "shard_backed", False))
        self._streaming_choice.data_is_shard_backed = shard_backed
        self._streaming_choice.shard_segment_bytes = getattr(
            sample, "shard_segment_bytes", None
        )
        import os as _os

        budget = (
            self.hbm_bytes if self.hbm_bytes is not None
            else device_memory_bytes()
        ) * self.hbm_utilization
        # An EXPLICIT host budget (constructor knob or env flag) is the
        # operator's chosen cap and is honored as-is; the utilization
        # derate applies only to autodetected physical RAM, where the
        # process/staging/page-cache headroom is unaccounted.
        env_budget = _os.environ.get("KEYSTONE_HOST_BUDGET_BYTES")
        if self.host_budget_bytes is not None:
            host_budget = float(self.host_budget_bytes)
        elif env_budget:
            host_budget = float(env_budget)
        else:
            host_budget = host_memory_bytes() * self.host_utilization
        # The streaming tier's feature slab scales down with the budget so
        # its capacity model and its actual tile sizing agree; the budget
        # itself drives its gram-vs-block tier decision.
        self._streaming_choice.slab_bytes = int(min(2 << 30, budget // 4))
        self._streaming_choice.budget_bytes = budget

        # What every NON-disk candidate needs host-side before any device
        # placement: the raw dataset plus labels, resident once.
        host_resident = (
            n * (raw_row_bytes if raw_row_bytes else 4.0 * d) + 4.0 * n * k
        )

        def resident(opt) -> float:
            rb = getattr(opt[0], "resident_bytes", None)
            if rb is None:
                return 0.0
            return rb(n, d, k, sparsity, machines)

        def host_ok(opt) -> bool:
            # The disk tier (shard-backed streaming choice) stages only
            # prefetch-depth segments host-side; everything else needs
            # the full dataset in host RAM to even begin.
            if shard_backed and opt[0] is self._streaming_choice:
                return True
            return host_resident <= host_budget

        def total_cost(opt) -> float:
            # Infeasible candidates — resident operands past the device
            # budget, or a dataset past the host-RAM budget with no disk
            # path — cost infinity: they would OOM, whatever their model
            # time says.
            if not host_ok(opt) or resident(opt) > budget:
                return float("inf")
            return opt[0].cost(
                n, d, k, sparsity, machines,
                self.cpu_weight, self.mem_weight, self.network_weight,
            )

        costs = [total_cost(opt) for opt in self.options]
        logger.debug(
            "LeastSquaresEstimator optimize: n=%d d=%d k=%d sparsity=%.4f "
            "machines=%d budget=%.2e costs=%s",
            n, d, k, sparsity, machines, budget,
            [f"{type(o[0]).__name__}={c:.3g}" for o, c in
             zip(self.options, costs)],
        )

        my_weights = (self.cpu_weight, self.mem_weight, self.network_weight)
        try:
            family = (
                weights_family_name()
                if my_weights == active_weights() else "custom"
            )
        except ValueError:  # broken calibrated artifact mid-process
            family = "custom"

        candidates = [
            {
                "label": candidate_label(o[0]),
                "cost_s": (None if c == float("inf") else float(c)),
                "feasible": c != float("inf"),
                "resident_bytes": float(resident(o)),
                "host_ok": host_ok(o),
            }
            for o, c in zip(self.options, costs)
        ]

        def emit_decision(winner, reason: str):
            # The structured audit event (obs plane, ISSUE 9): candidate
            # set, predicted costs, feasibility verdicts, winner —
            # tests/test_cost_replay.py's trace-backed audit leg asserts
            # the recorded winner matches every replay assertion.
            # Returns the CostOutcomeRef the executor later stamps the
            # winner's measured wall onto (obs/calibrate.py).
            return obs.record_cost_decision(obs.CostDecision(
                decision="least_squares_solver",
                winner=candidate_label(winner),
                candidates=candidates,
                reason=reason,
                context={
                    "n": int(n), "d": int(d), "k": int(k),
                    "sparsity": float(sparsity), "machines": int(machines),
                    "hbm_budget_bytes": float(budget),
                    "host_budget_bytes": float(host_budget),
                    "shard_backed": shard_backed,
                    "weights": {
                        "cpu": self.cpu_weight, "mem": self.mem_weight,
                        "network": self.network_weight,
                        "family": family,
                    },
                },
            ))

        # The global placement engine resolves the argmin (first minimum
        # — exactly int(np.argmin)) and, all-infeasible, the
        # least-resident fallback (exactly min(options, key=resident)):
        # the recorded winner is unchanged by construction, and the
        # unified placement.decision stream gets its mirror row.
        choice = PlacementEngine(weights_family=family).decide(
            KIND_SOLVER, candidates,
            context={
                "n": int(n), "d": int(d), "k": int(k),
                "sparsity": float(sparsity), "machines": int(machines),
                "hbm_budget_bytes": float(budget),
                "host_budget_bytes": float(host_budget),
                "shard_backed": shard_backed,
            },
            fallback="least_resident",
        )
        chosen = self.options[choice.index]
        if choice.reason == "least_resident_fallback":
            # Nothing fits the budget model: the least-resident
            # candidate (in practice the streaming tier) beats a
            # guaranteed OOM.
            logger.warning(
                "no solver candidate fits the %.2f GB budget at n=%d d=%d; "
                "selecting least-resident %s",
                budget / 2**30, n, d, type(chosen[0]).__name__,
            )
        # The pending back-annotation: whoever fits the winner (the
        # executor's fit_datasets, or a fused streamed fit that inherits
        # the ref) stamps the measured wall + span id onto the decision
        # record, closing the predicted-vs-measured loop per decision.
        chosen[1]._pending_cost_outcome = emit_decision(
            chosen[0], choice.reason
        )
        return chosen[1]
