"""Probabilistic / discriminant classifiers.

Reference: nodes/learning/NaiveBayesModel.scala:21-69 (multinomial NB),
LogisticRegressionModel.scala:42-94 (wraps MLlib LogisticRegressionWithLBFGS —
here an in-tree LBFGS-optimized softmax regression),
LinearDiscriminantAnalysis.scala:17-68 (multi-class LDA via eigendecomposition).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.linear import LinearMapper
from keystone_tpu.ops.sparse import densify_dataset
from keystone_tpu.workflow import LabelEstimator, Transformer

logger = logging.getLogger("keystone_tpu.classifiers")


class NaiveBayesModel(Transformer):
    """x -> log-prior + log-likelihood·x (unnormalized class log-posteriors)
    (reference: NaiveBayesModel.scala:21-54)."""

    def __init__(self, pi, theta):
        self.pi = jnp.asarray(pi)  # (k,) log priors, indexed by class
        self.theta = jnp.asarray(theta)  # (k, d) log feature likelihoods

    def apply(self, x):
        return self.pi + self.theta @ jnp.asarray(x)

    def batch_apply(self, data: Dataset) -> Dataset:
        data = densify_dataset(data, self.theta.shape[1])
        return data.map_batch(lambda X: X @ self.theta.T + self.pi)


class NaiveBayesEstimator(LabelEstimator):
    """Multinomial naive Bayes with additive smoothing λ
    (reference: NaiveBayesModel.scala:56-69, matching MLlib NaiveBayes.train)."""

    def __init__(self, num_classes: int, lam: float = 1.0):
        self.num_classes = num_classes
        self.lam = lam

    def fit(self, data: Dataset, labels: Dataset) -> NaiveBayesModel:
        X = jnp.asarray(densify_dataset(data).array)
        y = jnp.asarray(labels.array).reshape(-1).astype(jnp.int32)
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=X.dtype)
        # Padding rows are zero in X and map to class 0 in y; mask them out.
        npad = X.shape[0]
        mask = (jnp.arange(npad) < data.n).astype(X.dtype)
        onehot = onehot * mask[:, None]

        class_counts = jnp.sum(onehot, axis=0)  # (k,)
        feature_sums = onehot.T @ X  # (k, d)

        pi = jnp.log(class_counts + self.lam) - jnp.log(
            data.n + self.num_classes * self.lam
        )
        d = X.shape[1]
        theta = jnp.log(feature_sums + self.lam) - jnp.log(
            jnp.sum(feature_sums, axis=1, keepdims=True) + d * self.lam
        )
        return NaiveBayesModel(pi, theta)


class LogisticRegressionModel(Transformer):
    """x -> argmax class under softmax weights
    (reference: LogisticRegressionModel.scala:27-40)."""

    def __init__(self, weights):
        self.weights = jnp.asarray(weights)  # (d, k)

    def apply(self, x):
        return jnp.argmax(jnp.asarray(x) @ self.weights, axis=-1)

    def batch_apply(self, data: Dataset) -> Dataset:
        data = densify_dataset(data, self.weights.shape[0])
        return data.map_batch(lambda X: jnp.argmax(X @ self.weights, axis=-1))


@jax.jit
def _logistic_lbfgs(X, onehot, mask, W0, n, lam, num_iters, tol):
    """Multinomial logistic LBFGS core (module-level jit: one executable per
    shape, reused across fits)."""

    def loss_fn(W):
        logits = X @ W
        # log-sum-exp over classes; padding rows masked out of the sum.
        lse = jax.nn.logsumexp(logits, axis=1)
        ll = jnp.sum(logits * onehot, axis=1) - lse * mask
        nll = -jnp.sum(ll) / n
        return nll + 0.5 * lam * jnp.sum(W * W)

    solver = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry):
        W, state, _ = carry
        value, grad = value_and_grad(W, state=state)
        updates, state = solver.update(
            grad, state, W, value=value, grad=grad, value_fn=loss_fn
        )
        return optax.apply_updates(W, updates), state, grad

    def cond(carry):
        _, state, grad = carry
        count = optax.tree_utils.tree_get(state, "count")
        return (count < num_iters) & (optax.tree_utils.tree_norm(grad) > tol)

    state = solver.init(W0)
    g0 = jax.grad(loss_fn)(W0)
    W, _, _ = jax.lax.while_loop(cond, step, (W0, state, g0))
    return W, loss_fn(W)


class LogisticRegressionEstimator(LabelEstimator):
    """Softmax regression by L-BFGS over the full sharded batch — the in-tree
    replacement for MLlib's LogisticRegressionWithLBFGS
    (reference: LogisticRegressionModel.scala:42-94)."""

    def __init__(
        self,
        num_classes: int,
        reg_param: float = 0.0,
        num_iters: int = 100,
        convergence_tol: float = 1e-4,
        num_features: Optional[int] = None,
    ):
        self.num_classes = num_classes
        self.reg_param = reg_param
        self.num_iters = num_iters
        self.convergence_tol = convergence_tol
        self.num_features = num_features

    @property
    def weight(self) -> int:
        return self.num_iters + 1

    def fit(self, data: Dataset, labels: Dataset) -> LogisticRegressionModel:
        data = densify_dataset(data, self.num_features)
        X = jnp.asarray(data.array)
        y = jnp.asarray(labels.array).reshape(-1).astype(jnp.int32)
        n = data.n
        npad = X.shape[0]
        mask = (jnp.arange(npad) < n).astype(X.dtype)
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=X.dtype) * mask[:, None]
        lam = self.reg_param

        W0 = jnp.zeros((X.shape[1], self.num_classes), dtype=X.dtype)
        W, final_loss = _logistic_lbfgs(
            X, onehot, mask, W0,
            jnp.asarray(float(n), dtype=X.dtype),
            jnp.asarray(lam, dtype=X.dtype),
            jnp.asarray(self.num_iters),
            jnp.asarray(self.convergence_tol, dtype=X.dtype),
        )
        logger.info("logistic final loss: %s", float(final_loss))
        return LogisticRegressionModel(W)


class LinearDiscriminantAnalysis(LabelEstimator):
    """Multi-class LDA: top eigenvectors of Sw⁻¹·Sb
    (reference: LinearDiscriminantAnalysis.scala:17-68)."""

    def __init__(self, num_dimensions: int):
        self.num_dimensions = num_dimensions

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        X = np.asarray(data.to_numpy(), dtype=np.float64)
        y = np.asarray(labels.to_numpy()).reshape(-1).astype(np.int64)
        classes = np.unique(y)
        d = X.shape[1]
        total_mean = X.mean(axis=0)

        Sw = np.zeros((d, d))
        Sb = np.zeros((d, d))
        for c in classes:
            Xc = X[y == c]
            mu = Xc.mean(axis=0)
            centered = Xc - mu
            Sw += centered.T @ centered
            m = (mu - total_mean)[:, None]
            Sb += Xc.shape[0] * (m @ m.T)

        eigvals, eigvecs = np.linalg.eig(np.linalg.solve(Sw, Sb))
        order = np.argsort(-np.abs(eigvals))[: self.num_dimensions]
        W = np.real(eigvecs[:, order])
        return LinearMapper(W)
