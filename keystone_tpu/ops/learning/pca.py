"""PCA family + ZCA whitening (reference: nodes/learning/PCA.scala:19-248,
DistributedPCA.scala:21-74, ApproximatePCA.scala:22-85, ZCAWhitener.scala:12-80).

Three PCA algorithms, mirroring the reference's optimizable set:
  - local SVD on collected data (PCAEstimator / sgesvd),
  - distributed via TSQR of the mean-centered sharded matrix then local SVD
    of R (DistributedPCAEstimator / mlmatrix TSQR),
  - randomized sketch (ApproximatePCAEstimator / Halko-Martinsson-Tropp).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.parallel import linalg
from keystone_tpu.workflow import Estimator, Transformer
from keystone_tpu.workflow.optimizable import OptimizableEstimator


def enforce_matlab_sign_convention(pca):
    """Largest-|coefficient| element of each column gets a positive sign
    (reference: PCA.scala:238-247)."""
    pca = jnp.asarray(pca)
    col_max = jnp.max(pca, axis=0)
    abs_col_max = jnp.max(jnp.abs(pca), axis=0)
    signs = jnp.where(col_max == abs_col_max, 1.0, -1.0)
    return pca * signs[None, :]


def compute_pca(data, dims: int):
    """Principal directions of mean-centered rows: V[:, :dims] of the SVD,
    matlab sign convention (reference: PCA.scala:179-247)."""
    data = jnp.asarray(data)
    centered = data - jnp.mean(data, axis=0)
    _, _, vt = jnp.linalg.svd(centered, full_matrices=False)
    pca = enforce_matlab_sign_convention(vt.T)
    return pca[:, :dims]


class PCATransformer(Transformer):
    """x -> pcaMatᵀ x (reference: PCA.scala:19-30)."""

    def __init__(self, pca_mat):
        self.pca_mat = jnp.asarray(pca_mat)

    def apply(self, x):
        return jnp.asarray(x) @ self.pca_mat

    def batch_apply(self, data: Dataset) -> Dataset:
        return data.map_batch(lambda X: X @ self.pca_mat)


class BatchPCATransformer(Transformer):
    """Per-item (d, cols) matrix -> (dims, cols): pcaMatᵀ · in
    (reference: PCA.scala:37-43)."""

    def __init__(self, pca_mat):
        self.pca_mat = jnp.asarray(pca_mat)

    def apply(self, x):
        return self.pca_mat.T @ jnp.asarray(x)

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            return Dataset.of([np.asarray(self.apply(x)) for x in data.to_list()])
        return data.map_batch(lambda X: jnp.einsum("dk,ndc->nkc", self.pca_mat, X))


class PCAEstimator(Estimator):
    """Local PCA: collect sample rows, SVD on device (reference: PCA.scala:163-231)."""

    def __init__(self, dims: int):
        self.dims = dims

    def fit(self, data: Dataset) -> PCATransformer:
        X = jnp.asarray(data.to_numpy() if data.is_host else data.array[: data.n])
        return PCATransformer(compute_pca(X, self.dims))

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w) -> float:
        flops = n * d * d
        return max(cpu_w * flops, mem_w * n * d) + net_w * n * d


class DistributedPCAEstimator(Estimator):
    """PCA via TSQR of the mean-centered sharded matrix, then SVD of R
    (reference: DistributedPCA.scala:21-74; subsumes mlmatrix TSQR)."""

    def __init__(self, dims: int):
        self.dims = dims

    def fit(self, data: Dataset) -> PCATransformer:
        X = jnp.asarray(data.array)
        mean = jnp.sum(X, axis=0) / data.n
        centered = X - mean
        # Re-zero padding rows (centering made them -mean).
        centered = centered * (jnp.arange(X.shape[0]) < data.n)[:, None].astype(X.dtype)
        R = linalg.tsqr_r(centered, data.mesh)
        _, _, vt = jnp.linalg.svd(R, full_matrices=False)
        pca = enforce_matlab_sign_convention(vt.T)
        return PCATransformer(pca[:, : self.dims])

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w) -> float:
        flops = 2.0 * n * d * d / num_machines + (d ** 3) * math.log(max(num_machines, 2), 2)
        network = d * d * math.log(max(num_machines, 2), 2)
        return max(cpu_w * flops, mem_w * n * d / num_machines) + net_w * network


class ApproximatePCAEstimator(Estimator):
    """Randomized PCA, Halko-Martinsson-Tropp alg 4.4/5.1: Gaussian sketch +
    q power iterations of QR (reference: ApproximatePCA.scala:22-85)."""

    def __init__(self, dims: int, q: int = 10, p: int = 5, seed: int = 0):
        self.dims = dims
        self.q = q
        self.p = p
        self.seed = seed

    def fit(self, data: Dataset) -> PCATransformer:
        X = jnp.asarray(data.array)
        mean = jnp.sum(X, axis=0) / data.n
        A = (X - mean) * (jnp.arange(X.shape[0]) < data.n)[:, None].astype(X.dtype)
        l = self.dims + self.p
        omega = jax.random.normal(jax.random.key(self.seed), (A.shape[1], l), dtype=A.dtype)
        Y = A @ omega
        Q, _ = jnp.linalg.qr(Y)
        for _ in range(self.q):
            Z = A.T @ Q
            Qz, _ = jnp.linalg.qr(Z)
            Y = A @ Qz
            Q, _ = jnp.linalg.qr(Y)
        B = Q.T @ A  # (l, d)
        _, _, vt = jnp.linalg.svd(B, full_matrices=False)
        pca = enforce_matlab_sign_convention(vt.T)
        return PCATransformer(pca[:, : self.dims])

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w) -> float:
        flops = n * d * (self.dims + self.p) * (self.q + 1) / num_machines
        return max(cpu_w * flops, mem_w * n * d / num_machines) + net_w * d * (self.dims + self.p)


class LocalColumnPCAEstimator(Estimator):
    """Column-matrix PCA, local SVD: items are (d, cols) matrices whose columns
    are treated as points (reference: PCA.scala:45-77)."""

    def __init__(self, dims: int):
        self.dims = dims

    def fit(self, data: Dataset) -> BatchPCATransformer:
        cols = np.concatenate([np.asarray(x).T for x in data.to_list()], axis=0)
        return BatchPCATransformer(compute_pca(cols, self.dims))


class DistributedColumnPCAEstimator(Estimator):
    """Column-matrix PCA via the distributed path (reference: PCA.scala:79-116)."""

    def __init__(self, dims: int):
        self.dims = dims

    def fit(self, data: Dataset) -> BatchPCATransformer:
        cols = np.concatenate([np.asarray(x).T for x in data.to_list()], axis=0)
        ds = Dataset.of(cols)
        pca = DistributedPCAEstimator(self.dims).fit(ds)
        return BatchPCATransformer(pca.pca_mat)


class ColumnPCAEstimator(OptimizableEstimator):
    """Optimizable column PCA: sample-driven local-vs-distributed choice
    (reference: PCA.scala:118-156)."""

    def __init__(
        self,
        dims: int,
        num_machines: Optional[int] = None,
        cpu_weight: float = 3.8e-4,
        mem_weight: float = 2.9e-1,
        network_weight: float = 1.32,
    ):
        self.dims = dims
        self.num_machines = num_machines
        self.cpu_weight = cpu_weight
        self.mem_weight = mem_weight
        self.network_weight = network_weight
        self._local = LocalColumnPCAEstimator(dims)
        self._distributed = DistributedColumnPCAEstimator(dims)

    @property
    def default(self):
        return self._distributed

    def optimize(self, sample: Dataset):
        items = sample.to_list()
        if not items:
            return None
        d = np.asarray(items[0]).shape[0]
        cols_per_item = float(np.mean([np.asarray(x).shape[1] for x in items]))
        n = int(cols_per_item * getattr(sample, "total_n", sample.n))
        machines = self.num_machines or max(len(jax.devices()), 1)
        local_cost = PCAEstimator(self.dims).cost(
            n, d, self.dims, 1.0, machines,
            self.cpu_weight, self.mem_weight, self.network_weight)
        dist_cost = DistributedPCAEstimator(self.dims).cost(
            n, d, self.dims, 1.0, machines,
            self.cpu_weight, self.mem_weight, self.network_weight)
        return self._local if local_cost < dist_cost else self._distributed


class ZCAWhitener(Transformer):
    """(in − means) · whitener on per-item (rows, d) matrices
    (reference: ZCAWhitener.scala:12-18)."""

    def __init__(self, whitener, means):
        self.whitener = jnp.asarray(whitener)
        self.means = jnp.asarray(means)

    def apply(self, x):
        return (jnp.asarray(x) - self.means) @ self.whitener

    def batch_apply(self, data: Dataset) -> Dataset:
        return data.map_batch(lambda X: (X - self.means) @ self.whitener)


class ZCAWhitenerEstimator(Estimator):
    """V·diag((s²/(n−1)+ε)^−½)·Vᵀ from the SVD of the centered sample
    (reference: ZCAWhitener.scala:30-80)."""

    def __init__(self, eps: float = 0.1):
        self.eps = eps

    def fit(self, data: Dataset) -> ZCAWhitener:
        # The reference fits on the first item (a sample matrix).
        first = data.to_list()[0] if data.is_host else np.asarray(data.array[0])
        return self.fit_single(jnp.asarray(first))

    def fit_single(self, X) -> ZCAWhitener:
        X = jnp.asarray(X)
        means = jnp.mean(X, axis=0)
        centered = X - means
        _, s, vt = jnp.linalg.svd(centered, full_matrices=False)
        s2 = (s * s) / (X.shape[0] - 1.0)
        scaled = jnp.diag((s2 + self.eps) ** -0.5)
        whitener = vt.T @ scaled @ vt
        return ZCAWhitener(whitener, means)


def _zca_cov_fold(sums, gram, X):
    """One segment's contribution to (Σx, XᵀX). Exact-f32 gram (HIGHEST:
    the eigendecomposition downstream amplifies covariance error by
    (λ+ε)^−3/2); zero-padded tail rows contribute zero to both terms, so
    no masking is needed — only the true-row count matters."""
    sums = sums + jnp.sum(X, axis=0)
    gram = gram + jax.lax.dot_general(
        X, X,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    return sums, gram


class StreamedZCAWhitenerEstimator(Estimator):
    """ZCA whitening as a streamed covariance fold over a
    :class:`~keystone_tpu.data.prefetch.ShardSource` — the out-of-core
    form of :class:`ZCAWhitenerEstimator` for patch sets that never fit
    in host RAM.

    Algebra: the batch estimator's SVD singular values satisfy
    s²/(n−1) = eigvals of the centered covariance, so folding
    (Σx, XᵀX, n) and finalizing with

        μ = Σx/n,  C = (XᵀX − n·μμᵀ)/(n−1),  C = V·Λ·Vᵀ,
        whitener = V·diag((Λ+ε)^−½)·Vᵀ

    reproduces ``fit_single`` up to eigenbasis roundoff (pinned in
    tests/test_zca_stream.py). The fold rides the standard streaming
    stack: segments arrive through ``iter_segments`` (prefetched on the
    read lane), and the (Σx, XᵀX, n) carry snapshots through
    :class:`~keystone_tpu.data.durable.CheckpointSpec` — a fit killed
    mid-stream and re-run with the same spec resumes BIT-IDENTICALLY
    (chaos-marked test, same discipline as the streamed gram solvers).
    """

    def __init__(
        self,
        eps: float = 0.1,
        checkpoint=None,
        prefetch_depth: int = 2,
    ):
        self.eps = eps
        self.checkpoint = checkpoint
        self.prefetch_depth = prefetch_depth

    def fit(self, data: Dataset) -> ZCAWhitener:
        if getattr(data, "is_shard_backed", False):
            return self.fit_source(data.shard_source)
        X = jnp.asarray(data.to_numpy() if data.is_host else data.array[: data.n])
        return ZCAWhitenerEstimator(self.eps).fit_single(X)

    def fit_source(self, source, stats=None) -> ZCAWhitener:
        """Fold (Σx, XᵀX, n) over the source's segments and finalize.

        Segment payloads may be ``(X, Y, valid_rows)`` triples (the
        DenseShardSource / image-tier contract; X is flattened to rows)
        or bare row blocks — those count all rows as true, clamped
        against the source's declared ``n_true``: fixed-shape shard
        views (``DenseShardView``) zero-pad the tail segment, and pad
        rows are zero in (Σx, XᵀX) but must not inflate ``n`` or the
        mean/covariance shrink toward zero."""
        from keystone_tpu.data.durable import (
            resolve_checkpoint,
            source_fingerprint,
        )
        from keystone_tpu.data.prefetch import iter_segments

        checkpoint = resolve_checkpoint(self.checkpoint)
        num_segments = int(source.num_segments)

        # Row width from the source's shape metadata when it has any
        # (EncodedImageSource.d, DenseShardSource.d_in, DenseShardView
        # .width). load(0) is only the fallback for bare sources: on an
        # image source it would decode a whole extra segment — and fire
        # the decode/augment fault sites once more — even when a
        # checkpoint restore resumes past segment 0.
        d = next(
            (
                int(v)
                for attr in ("d", "d_in", "width")
                if (v := getattr(source, attr, None)) is not None
            ),
            None,
        )
        if d is None:
            d = int(self._rows(source.load(0))[0].shape[-1])

        sums = jnp.zeros((d,), jnp.float32)
        gram = jnp.zeros((d, d), jnp.float32)
        count = 0
        start_seg = 0
        fingerprint = None
        if checkpoint is not None:
            fingerprint = {
                "kind": "zca_stream",
                "eps": float(self.eps),
                "d": d,
                "num_segments": num_segments,
                "source": source_fingerprint(source),
            }
            arrays, start_seg = checkpoint.restore(fingerprint)
            if arrays is not None:
                sums = jnp.asarray(arrays[0])
                gram = jnp.asarray(arrays[1])
                count = int(np.asarray(arrays[2])[0])

        fold = jax.jit(_zca_cov_fold)
        n_true = getattr(source, "n_true", None)
        for s, payload in iter_segments(
            source,
            prefetch_depth=self.prefetch_depth,
            stats=stats,
            start=start_seg,
        ):
            X, valid = self._rows(payload)
            sums, gram = fold(sums, gram, jnp.asarray(X, jnp.float32))
            if n_true is not None:
                valid = min(valid, int(n_true) - count)
            count += valid
            if checkpoint is not None:
                checkpoint.maybe_save(
                    [sums, gram, np.asarray([count], np.int64)],
                    s, num_segments, fingerprint, stats=stats,
                )
        if checkpoint is not None:
            checkpoint.clear(fingerprint)
        return self._finalize(sums, gram, count)

    @staticmethod
    def _rows(payload):
        """Normalize a segment payload to (rows (r, d), valid_count)."""
        if isinstance(payload, tuple):
            X = np.asarray(payload[0])
            valid = int(payload[2]) if len(payload) > 2 else X.shape[0]
        else:
            X = np.asarray(payload)
            valid = X.shape[0]
        return X.reshape(-1, X.shape[-1]), valid

    def _finalize(self, sums, gram, n: int) -> ZCAWhitener:
        if n < 2:
            raise ValueError(f"streamed ZCA needs n >= 2 rows, saw {n}")
        means = sums / n
        cov = (gram - n * jnp.outer(means, means)) / (n - 1.0)
        lam, V = jnp.linalg.eigh(cov)
        # eigh of a PSD-up-to-roundoff covariance can return tiny
        # negative eigenvalues; clamp before the inverse square root.
        scaled = (jnp.maximum(lam, 0.0) + self.eps) ** -0.5
        whitener = (V * scaled[None, :]) @ V.T
        return ZCAWhitener(whitener, means)

    def cost(self, n, d, k, sparsity, num_machines, cpu_w, mem_w, net_w) -> float:
        flops = n * d * d + d ** 3
        # Streaming holds one (d, d) gram + a segment, not the n×d matrix.
        return max(cpu_w * flops, mem_w * d * d) + net_w * d * d
