"""Clustering: k-means++ and diagonal-covariance GMM.

Reference: nodes/learning/KMeansPlusPlus.scala:16-181,
GaussianMixtureModel.scala:19-110, GaussianMixtureModelEstimator.scala:25-203.

Lloyd's iterations and EM are expressed as whole-batch GEMMs (distance and
responsibility computations are n×k matmuls on the MXU); the k-means++
seeding's sequential multinomial draws run on host over the collected sample,
as in the reference.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.workflow import Estimator, Transformer

logger = logging.getLogger("keystone_tpu.clustering")


class KMeansModel(Transformer):
    """Assign each point a one-hot nearest-center indicator
    (reference: KMeansPlusPlus.scala:16-70)."""

    def __init__(self, means):
        self.means = jnp.asarray(means)  # (k, d)

    def apply(self, x):
        return self.assignments(jnp.asarray(x)[None])[0]

    def assignments(self, X):
        sq_dist = (
            0.5 * jnp.sum(X * X, axis=1, keepdims=True)
            - X @ self.means.T
            + 0.5 * jnp.sum(self.means * self.means, axis=1)[None, :]
        )
        nearest = jnp.argmin(sq_dist, axis=1)
        return jax.nn.one_hot(nearest, self.means.shape[0], dtype=X.dtype)

    def batch_apply(self, data: Dataset) -> Dataset:
        return data.map_batch(self.assignments)


@jax.jit
def _lloyd_loop(Xd, means, stop_tolerance, max_iterations):
    """Lloyd's iterations: the whole (step + convergence check) loop is ONE
    compiled program (lax.while_loop) — no per-iteration host round trips,
    unlike the reference's driver-checked loop. Module-level jit: one
    executable per shape, reused across fits."""
    num_means = means.shape[0]

    def lloyd_step(means):
        sq_dist = (
            0.5 * jnp.sum(Xd * Xd, axis=1, keepdims=True)
            - Xd @ means.T
            + 0.5 * jnp.sum(means * means, axis=1)[None, :]
        )
        cost = jnp.mean(jnp.min(sq_dist, axis=1))
        assign = jax.nn.one_hot(
            jnp.argmin(sq_dist, axis=1), num_means, dtype=Xd.dtype
        )
        mass = jnp.sum(assign, axis=0)
        new_means = (assign.T @ Xd) / jnp.maximum(mass, 1e-12)[:, None]
        # Keep empty clusters where they were rather than collapsing to 0.
        new_means = jnp.where((mass > 0)[:, None], new_means, means)
        return new_means, cost

    def cond(carry):
        it, _, prev_cost, cost = carry
        not_converged = (prev_cost - cost) >= (stop_tolerance * jnp.abs(prev_cost))
        return (it < max_iterations) & ((it < 2) | not_converged)

    def body(carry):
        it, means, _, cost = carry
        new_means, new_cost = lloyd_step(means)
        return it + 1, new_means, cost, new_cost

    inf = jnp.asarray(jnp.inf, dtype=Xd.dtype)
    it, means_out, _, cost = jax.lax.while_loop(cond, body, (0, means, inf, inf))
    return it, means_out, cost


class KMeansPlusPlusEstimator(Estimator):
    """k-means++ seeding + Lloyd's iterations with cost-improvement stopping
    (reference: KMeansPlusPlus.scala:83-180)."""

    def __init__(
        self,
        num_means: int,
        max_iterations: int,
        stop_tolerance: float = 1e-3,
        seed: int = 0,
    ):
        self.num_means = num_means
        self.max_iterations = max_iterations
        self.stop_tolerance = stop_tolerance
        self.seed = seed

    def fit(self, data: Dataset) -> KMeansModel:
        X = np.asarray(data.to_numpy(), dtype=np.float64)
        return self.fit_array(X)

    def fit_array(self, X: np.ndarray) -> KMeansModel:
        n = X.shape[0]
        rng = np.random.default_rng(self.seed)
        x_sq_half = 0.5 * np.sum(X * X, axis=1)

        # -- k-means++ seeding: sequential multinomial draws over sq-distances.
        centers = np.zeros(self.num_means, dtype=np.int64)
        centers[0] = rng.integers(0, n)
        cur_sq_dist = None
        for k in range(self.num_means - 1):
            c = X[centers[k]]
            sq_to_new = x_sq_half - X @ c + 0.5 * (c @ c)
            cur_sq_dist = (
                sq_to_new if cur_sq_dist is None else np.minimum(sq_to_new, cur_sq_dist)
            )
            probs = np.maximum(cur_sq_dist, 0.0)
            total = probs.sum()
            if total <= 0:
                centers[k + 1] = rng.integers(0, n)
            else:
                centers[k + 1] = rng.choice(n, p=probs / total)

        means = jnp.asarray(X[centers])
        Xd = jnp.asarray(X)

        it, means, cost = _lloyd_loop(
            Xd, means,
            jnp.asarray(self.stop_tolerance, dtype=Xd.dtype),
            jnp.asarray(self.max_iterations),
        )
        it = int(it)
        logger.info(
            "KMeans stopped after %d iterations (max %d, %s), cost %f",
            it,
            self.max_iterations,
            "converged" if it < self.max_iterations else "iteration cap",
            float(cost),
        )
        return KMeansModel(means)


class GaussianMixtureModel(Transformer):
    """Thresholded posterior assignments under a diagonal-covariance GMM
    (reference: GaussianMixtureModel.scala:19-95).

    means/variances: (d, k) as in the reference; weights: (k,).
    """

    def __init__(self, means, variances, weights, weight_threshold: float = 1e-4):
        self.means = jnp.asarray(means)
        self.variances = jnp.asarray(variances)
        self.weights = jnp.asarray(weights)
        self.weight_threshold = weight_threshold
        if self.means.shape != self.variances.shape:
            raise ValueError("GMM means and variances must be the same size.")
        if self.weights.shape[0] != self.means.shape[1]:
            raise ValueError("Every GMM center must have a weight.")

    @property
    def k(self) -> int:
        return self.means.shape[1]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def posteriors(self, X):
        mu = self.means.T  # (k, d)
        var = self.variances.T  # (k, d)
        # Squared Mahalanobis via GEMMs (GaussianMixtureModel.scala:53-57).
        sq_mahl = (
            (X * X) @ (0.5 / var).T
            - X @ (mu / var).T
            + 0.5 * jnp.sum(mu * mu / var, axis=1)[None, :]
        )
        llh = (
            -0.5 * X.shape[1] * jnp.log(2 * jnp.pi)
            - 0.5 * jnp.sum(jnp.log(var), axis=1)[None, :]
            + jnp.log(self.weights)[None, :]
            - sq_mahl
        )
        llh = llh - jnp.max(llh, axis=1, keepdims=True)
        post = jnp.exp(llh)
        post = post / jnp.sum(post, axis=1, keepdims=True)
        # Aggressive posterior thresholding (GaussianMixtureModel.scala:76-80).
        post = jnp.where(post > self.weight_threshold, post, 0.0)
        return post / jnp.sum(post, axis=1, keepdims=True)

    def apply(self, x):
        return self.posteriors(jnp.asarray(x)[None])[0]

    def batch_apply(self, data: Dataset) -> Dataset:
        return data.map_batch(self.posteriors)

    @staticmethod
    def load(mean_file: str, vars_file: str, weights_file: str) -> "GaussianMixtureModel":
        """CSV load (reference: GaussianMixtureModel.scala:103-110)."""
        means = np.loadtxt(mean_file, delimiter=",", ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=",", ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=",").reshape(-1)
        return GaussianMixtureModel(means, variances, weights)


@jax.jit
def _em_loop(Xd, mu, var, w, key, x_var, floor_var, small_threshold, tol,
             max_iterations, abs_var_floor, rel_var_floor):
    """Whole EM loop as one program: step + variance floors + collapsed-
    cluster restarts + convergence, no host round trips. Module-level jit:
    one executable per shape, reused across fits."""
    n, d = Xd.shape
    k = mu.shape[0]

    def em_step(mu, var, w):
        sq_mahl = (
            (Xd * Xd) @ (0.5 / var).T
            - Xd @ (mu / var).T
            + 0.5 * jnp.sum(mu * mu / var, axis=1)[None, :]
        )
        llh = (
            -0.5 * d * jnp.log(2 * jnp.pi)
            - 0.5 * jnp.sum(jnp.log(var), axis=1)[None, :]
            + jnp.log(w)[None, :]
            - sq_mahl
        )
        m = jnp.max(llh, axis=1, keepdims=True)
        log_norm = m + jnp.log(jnp.sum(jnp.exp(llh - m), axis=1, keepdims=True))
        post = jnp.exp(llh - log_norm)
        nk = jnp.sum(post, axis=0)
        new_mu = (post.T @ Xd) / nk[:, None]
        ex2 = (post.T @ (Xd * Xd)) / nk[:, None]
        new_var = ex2 - new_mu * new_mu
        new_w = nk / n
        return new_mu, new_var, new_w, jnp.mean(log_norm), nk

    def cond(carry):
        it, _, _, _, prev_ll, ll, _ = carry
        not_converged = jnp.abs(ll - prev_ll) >= (
            tol * jnp.maximum(jnp.abs(prev_ll), 1.0)
        )
        return (it < max_iterations) & ((it < 2) | not_converged)

    def body(carry):
        it, mu, var, w, _, ll, key = carry
        new_mu, new_var, new_w, new_ll, nk = em_step(mu, var, w)
        # Variance floors: max(smallVarianceThreshold · GLOBAL per-dim data
        # variance, absolute floor), fixed before EM
        # (GaussianMixtureModelEstimator.scala:100 gmmVarLB). floor_var is
        # the EXACT data variance — x_var carries a +1e-6 init regularizer
        # that would lift constant dimensions off the absolute floor.
        floor = jnp.maximum(abs_var_floor, rel_var_floor * floor_var[None, :])
        new_var = jnp.maximum(new_var, floor)
        # Restart clusters that collapsed below the minimum size with random
        # data points (device RNG replaces the host draws). Distinct indices
        # (choice without replacement): clusters restarted in the same
        # iteration must not collapse onto the same reseed point.
        key, sub = jax.random.split(key)
        small = nk < small_threshold
        idx = jax.random.choice(sub, n, (min(k, n),), replace=False)
        idx = jnp.resize(idx, (k,))
        new_mu = jnp.where(small[:, None], Xd[idx], new_mu)
        new_var = jnp.where(small[:, None], x_var[None, :], new_var)
        new_w = jnp.where(small, 1.0 / k, new_w)
        new_w = new_w / jnp.sum(new_w)
        return it + 1, new_mu, new_var, new_w, ll, new_ll, key

    neg_inf = jnp.asarray(-jnp.inf, dtype=Xd.dtype)
    init = (0, mu, var, w, neg_inf, neg_inf, key)
    it, mu, var, w, _, ll, _ = jax.lax.while_loop(cond, body, init)
    return it, mu, var, w, ll


class GaussianMixtureModelEstimator(Estimator):
    """Diagonal-covariance GMM via local EM over the collected sample, k-means++
    (or random) init, variance lower bounds, min-cluster-size restarts
    (reference: GaussianMixtureModelEstimator.scala:25-203)."""

    def __init__(
        self,
        k: int,
        max_iterations: int = 100,
        tol: float = 1e-4,
        min_cluster_size: int = 40,
        absolute_variance_floor: float = 1e-9,
        # smallVarianceThreshold default (GaussianMixtureModelEstimator.scala:31).
        relative_variance_floor: float = 1e-2,
        kmeans_init: bool = True,
        seed: int = 0,
    ):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.min_cluster_size = min_cluster_size
        self.absolute_variance_floor = absolute_variance_floor
        self.relative_variance_floor = relative_variance_floor
        self.kmeans_init = kmeans_init
        self.seed = seed

    def fit(self, data: Dataset) -> GaussianMixtureModel:
        X = np.asarray(data.to_numpy(), dtype=np.float64)
        return self.fit_array(X)

    def fit_array(self, X: np.ndarray) -> GaussianMixtureModel:
        n, d = X.shape
        rng = np.random.default_rng(self.seed)

        if self.kmeans_init:
            km = KMeansPlusPlusEstimator(self.k, 10, seed=self.seed).fit_array(X)
            # np.array (copy): np.asarray of a jax array is a read-only view,
            # and the restart logic below mutates mu in place.
            mu = np.array(km.means)
        else:
            mu = X[rng.choice(n, self.k, replace=False)]
        exact_var = X.var(axis=0)
        base_var = exact_var + 1e-6  # init/restart stability fudge only
        var = np.tile(base_var, (self.k, 1))
        w = np.full(self.k, 1.0 / self.k)

        Xd = jnp.asarray(X)
        x_var = jnp.asarray(base_var)
        floor_var = jnp.asarray(exact_var)
        small_threshold = min(self.min_cluster_size, n / (2 * self.k))

        key = jax.random.key(int(rng.integers(0, 2**31 - 1)))
        it, mu_j, var_j, w_j, ll = _em_loop(
            Xd, jnp.asarray(mu), jnp.asarray(var), jnp.asarray(w), key, x_var,
            floor_var,
            jnp.asarray(small_threshold, dtype=Xd.dtype),
            jnp.asarray(self.tol, dtype=Xd.dtype),
            jnp.asarray(self.max_iterations),
            jnp.asarray(self.absolute_variance_floor, dtype=Xd.dtype),
            jnp.asarray(self.relative_variance_floor, dtype=Xd.dtype),
        )
        it = int(it)
        logger.info(
            "GMM EM stopped after %d iterations (max %d, %s), mean llh %f",
            it,
            self.max_iterations,
            "converged" if it < self.max_iterations else "iteration cap",
            float(ll),
        )
        mu, var, w = np.array(mu_j), np.array(var_j), np.array(w_j)

        # Reference layout: (d, k).
        return GaussianMixtureModel(mu.T, var.T, w)
