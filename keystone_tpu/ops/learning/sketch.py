"""Randomized sketched least-squares engines (PAPERS.md: "Faster Least
Squares Approximation", "Iterative Hessian Sketch in Input Sparsity
Time").

Two engines beyond the exact gram/gather tiers, both streamed
chunk-by-chunk over the same padded-COO operand tiles the gram fold
consumes (``data.resident.raw_chunk_tiles`` /
:class:`~keystone_tpu.data.resident.CompressedCOOChunks`), so they
compose with the prefetch/resident storage tiers:

- :class:`SketchedLeastSquares` — SRHT sketch-and-precondition. Each
  chunk is sign-flipped, mixed with a padded real FFT
  (``stats.srht_chunk_sketch`` — the fourth caller of the shared
  ``rfft_real_half`` epilogue) and row-sampled; stacking the per-chunk
  samples gives a block-diagonal SRHT of the whole row stream. One QR
  of the sketched matrix yields a preconditioner, then preconditioned
  CG iterates on the ORIGINAL operator (gather/segment-sum passes) to
  full accuracy: the sketch buys conditioning, not the answer, so the
  solution is exact up to CG tolerance.

- :class:`IterativeHessianSketch` — CountSketch folds in
  input-sparsity time: O(nnz) scatter-adds per pass, no densified
  slab ever exists. Each outer iteration draws a FRESH sketch, folds
  the sketched Hessian and the exact gradient in ONE pass over the
  chunk tiles, and takes the guarded Newton-sketch step
  ``X -= (SAᵀSA/n + λI)⁻¹ g`` (Pilanci & Wainwright). The exact
  gradient keeps every accepted step a true descent direction even
  when ``m ~ 4d`` is far below the oblivious-embedding bound.

Both are :class:`~keystone_tpu.workflow.LabelEstimator` candidates
priced by ``cost.py`` under ``allow_approximate=True``, each with its
own calibrated weight family (``srht_sketch_overhead`` /
``countsketch_overhead`` — obs/calibrate.py refits them from traces
like the gather overhead). Randomized draws all derive from the
explicit integer ``seed`` (the explicit-seed lint rule,
tools/lint.py).
"""

from __future__ import annotations

import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.stats import padded_pow2, srht_chunk_sketch
from keystone_tpu.workflow import LabelEstimator

logger = logging.getLogger("keystone_tpu.sketch")

# Ridge floor added to sketched Gramians / preconditioners so lam=0
# problems still factor (matches linear.SketchedLeastSquaresEstimator).
_EPS = 1e-8


def _densify(idx, val, d: int):
    """(c, w) padded-COO lanes -> (c, d) f32 slab; −1 / out-of-range
    lanes masked (the sparse_gram_fold densify convention)."""
    mask = (idx >= 0) & (idx < d)
    safe = jnp.where(mask, idx, 0).astype(jnp.int32)
    vals = jnp.where(mask, val, 0).astype(jnp.float32)
    rows = jnp.broadcast_to(jnp.arange(idx.shape[0])[:, None], idx.shape)
    return jnp.zeros((idx.shape[0], d), jnp.float32).at[rows, safe].add(vals)


def _append_intercept(indices, values, n: int, d: int):
    """Append-ones intercept lane at column d (LBFGS.scala:208-281);
    padding rows get an inactive (−1) lane."""
    npad = indices.shape[0]
    valid = jnp.arange(npad) < n
    idx1 = jnp.concatenate(
        [indices, jnp.where(valid, d, -1)[:, None].astype(indices.dtype)],
        axis=1,
    )
    val1 = jnp.concatenate(
        [values, valid.astype(values.dtype)[:, None]], axis=1
    )
    return idx1, val1


def _pcg(matvec, precond, b, iters: int, tol: float):
    """Preconditioned CG on ``matvec(x) = b``, all k right-hand sides
    vectorized (per-column alpha/beta). Columns freeze once their
    residual drops below ``tol * ||b||`` — the remaining iterations
    are no-ops for them, so a converged column cannot divide by a
    vanishing curvature."""
    x = jnp.zeros_like(b)
    r = b
    z = precond(r)
    p = z
    rz = jnp.sum(r * z, axis=0)
    bnorm = jnp.sqrt(jnp.sum(b * b, axis=0))
    floor = tol * jnp.maximum(bnorm, 1e-30)

    def body(_, state):
        x, r, p, rz = state
        active = jnp.sqrt(jnp.sum(r * r, axis=0)) > floor
        Hp = matvec(p)
        pHp = jnp.sum(p * Hp, axis=0)
        alpha = jnp.where(active, rz / jnp.where(pHp == 0, 1.0, pHp), 0.0)
        x = x + alpha * p
        r = r - alpha * Hp
        z = precond(r)
        rz_new = jnp.sum(r * z, axis=0)
        beta = jnp.where(active, rz_new / jnp.where(rz == 0, 1.0, rz), 0.0)
        p = jnp.where(active, z + beta * p, p)
        return x, r, p, rz_new

    x, _, _, _ = jax.lax.fori_loop(0, iters, body, (x, r, p, rz))
    return x


def _chol_precond(R):
    """x -> R⁻¹ R⁻ᵀ x for upper-triangular R (two triangular solves) —
    the SRHT preconditioner apply."""
    from jax.scipy.linalg import solve_triangular

    def apply(v):
        y = solve_triangular(R, v, trans="T", lower=False)
        return solve_triangular(R, y, lower=False)

    return apply


class SketchedLeastSquares(LabelEstimator):
    """SRHT sketch-and-precondition ridge solver (Drineas et al.).

    Streams the row chunks once to build the block-SRHT sketch ``S A``
    (sign-flip -> padded rfft along the row axis -> sample ``m/nchunks``
    frequency bins per chunk) plus ``AᵀB`` in the same scan, takes
    ``R = qr([SA/√n; √λ I])`` as a preconditioner for the ridge Hessian
    ``AᵀA/n + λI``, then runs preconditioned CG with one gather +
    segment-sum data pass per iteration. A well-sized sketch
    (``sketch_size ≈ 2d``) clusters the preconditioned spectrum near 1,
    so ~10 CG passes replace the 20+ an unpreconditioned iterative
    solver needs — the data passes, not the sketch, dominate the wall.

    ``sketch_size`` is the total sketched row count ``m`` (default
    ``sketch_factor * (d+1)``), the knob trading preconditioner quality
    against sketch wall; the bench frontier row sweeps it.
    """

    def __init__(
        self,
        lam: float = 0.0,
        sketch_size: Optional[int] = None,
        sketch_factor: int = 2,
        pcg_iters: int = 12,
        convergence_tol: float = 1e-6,
        seed: int = 0,
        chunk_rows: int = 8192,
        num_features: Optional[int] = None,
    ):
        self.lam = lam
        self.sketch_size = sketch_size
        self.sketch_factor = sketch_factor
        self.pcg_iters = pcg_iters
        self.convergence_tol = convergence_tol
        self.seed = seed
        self.chunk_rows = chunk_rows
        self.num_features = num_features
        # Overheads resolved at CONSTRUCTION like every engine's weights
        # (a mid-process KEYSTONE_COST_WEIGHTS flip must not mix weight
        # families within one selector's ranking).
        from keystone_tpu.ops.learning import cost as cost_mod

        self._sketch_overhead = cost_mod.srht_sketch_overhead()
        self._gather_overhead = cost_mod.sparse_gather_overhead()

    @property
    def weight(self) -> int:
        return self.pcg_iters + 1

    def _resolve_m(self, d1: int) -> int:
        return int(self.sketch_size or self.sketch_factor * d1)

    def fit(self, data: Dataset, labels: Dataset):
        from keystone_tpu.ops.sparse import is_sparse_dataset
        from keystone_tpu.ops.learning.linear import (
            LinearMapper, SparseLinearMapper,
        )

        B = jnp.asarray(labels.array).astype(jnp.float32)
        if is_sparse_dataset(data):
            indices = jnp.asarray(data.data["indices"])
            values = jnp.asarray(data.data["values"])
            d = self.num_features or int(jnp.max(indices)) + 1
            idx1, val1 = _append_intercept(indices, values, data.n, d)
            W1 = self._fit_sparse(idx1, val1, B, d + 1, data.n)
            return SparseLinearMapper(W1[:-1], b_opt=W1[-1])
        A = jnp.asarray(data.array).astype(jnp.float32)
        npad = A.shape[0]
        ones = (jnp.arange(npad) < data.n).astype(A.dtype)[:, None]
        A1 = jnp.concatenate([A, ones], axis=1)
        W1 = self._fit_dense(A1, B, data.n)
        return LinearMapper(W1[:-1], b_opt=W1[-1])

    def _sketch_stream(self, chunk_fn, nchunks: int, c: int, d1: int, Y_t):
        """One scan over the row chunks producing the stacked block-SRHT
        sketch (nchunks*m_pc, d1) and AᵀB — the only pass that ever
        densifies, and only one chunk-slab at a time."""
        p = padded_pow2(c)
        m_pc = max(1, min(-(-self._resolve_m(d1) // nchunks), p // 2))
        # E[(Re F z)_k²] ≈ ‖z‖²/2 under random signs, so √(2/m_pc) makes
        # each chunk's sampled block an isometry in expectation.
        scale = math.sqrt(2.0 / m_pc)
        key = jax.random.key(self.seed)
        k = Y_t.shape[-1]

        def step(AtB, cid):
            idx, val, y = chunk_fn(cid)
            dense = _densify(idx, val, d1)
            kc = jax.random.fold_in(key, cid)
            ks, kb = jax.random.split(kc)
            signs = jax.random.rademacher(ks, (c,), dtype=jnp.float32)
            bins = jax.random.randint(kb, (m_pc,), 0, p // 2)
            SA_c = srht_chunk_sketch(dense, signs, bins, scale)
            return AtB + dense.T @ y.astype(jnp.float32), SA_c

        AtB, SA_chunks = jax.lax.scan(
            step, jnp.zeros((d1, k), jnp.float32), jnp.arange(nchunks)
        )
        return SA_chunks.reshape(nchunks * m_pc, d1), AtB

    def _solve(self, SA, AtB, matvec, n: int, d1: int):
        """QR the (scaled, ridge-augmented) sketch, PCG on the original
        operator."""
        ridge = math.sqrt(self.lam + _EPS)
        M = jnp.concatenate(
            [SA / math.sqrt(n), ridge * jnp.eye(d1, dtype=SA.dtype)], axis=0
        )
        R = jnp.linalg.qr(M, mode="r")
        X = _pcg(
            matvec, _chol_precond(R), AtB / n,
            iters=self.pcg_iters, tol=self.convergence_tol,
        )
        return X

    def _fit_sparse(self, idx1, val1, B, d1: int, n: int):
        from keystone_tpu.data.resident import raw_chunk_tiles
        from keystone_tpu.ops.sparse import sparse_matmul, sparse_matmul_t

        c = min(self.chunk_rows, idx1.shape[0])
        idx_t, val_t, Y_t = raw_chunk_tiles(idx1, val1, B, c)
        nchunks = int(idx_t.shape[0])
        SA, AtB = self._sketch_stream(
            lambda cid: (idx_t[cid], val_t[cid], Y_t[cid]),
            nchunks, c, d1, Y_t,
        )

        def matvec(V):
            rows = sparse_matmul(idx1, val1, V)
            return sparse_matmul_t(idx1, val1, rows, d1) / n + self.lam * V

        return self._solve(SA, AtB, matvec, n, d1)

    def _fit_dense(self, A1, B, n: int):
        d1 = A1.shape[1]
        c = min(self.chunk_rows, A1.shape[0])
        nchunks = -(-A1.shape[0] // c)
        pad = nchunks * c - A1.shape[0]
        A_t = jnp.pad(A1, ((0, pad), (0, 0))).reshape(nchunks, c, d1)
        Y_t = jnp.pad(B, ((0, pad), (0, 0))).reshape(nchunks, c, B.shape[1])
        p = padded_pow2(c)
        m_pc = max(1, min(-(-self._resolve_m(d1) // nchunks), p // 2))
        scale = math.sqrt(2.0 / m_pc)
        key = jax.random.key(self.seed)

        def step(AtB, cid):
            dense = A_t[cid]
            kc = jax.random.fold_in(key, cid)
            ks, kb = jax.random.split(kc)
            signs = jax.random.rademacher(ks, (c,), dtype=jnp.float32)
            bins = jax.random.randint(kb, (m_pc,), 0, p // 2)
            SA_c = srht_chunk_sketch(dense, signs, bins, scale)
            return AtB + dense.T @ Y_t[cid], SA_c

        AtB, SA_chunks = jax.lax.scan(
            step, jnp.zeros((d1, B.shape[1]), jnp.float32),
            jnp.arange(nchunks),
        )
        SA = SA_chunks.reshape(nchunks * m_pc, d1)

        def matvec(V):
            return A1.T @ (A1 @ V) / n + self.lam * V

        return self._solve(SA, AtB, matvec, n, d1)

    def cost(
        self, n, d, k, sparsity, num_machines,
        cpu_weight, mem_weight, network_weight,
        sketch_overhead: Optional[float] = None,
        gather_overhead: Optional[float] = None,
    ) -> float:
        """One sketch pass (densify scatter at the SRHT random-write rate
        plus the bandwidth-bound FFT mixing passes), one QR of the
        (m, d) sketch, then ``pcg_iters`` gather-engine data passes."""
        if sketch_overhead is None:
            sketch_overhead = self._sketch_overhead
        if gather_overhead is None:
            gather_overhead = self._gather_overhead
        m = self._resolve_m(int(d) + 1)
        nnz = n * sparsity * d
        sketch = (
            sketch_overhead * mem_weight * nnz
            + mem_weight * 3.0 * n * d
        ) / num_machines
        qr = cpu_weight * 2.0 * m * d * d / num_machines
        per_pass = (
            gather_overhead
            * max(cpu_weight * nnz * k, mem_weight * nnz) / num_machines
        )
        network = (
            network_weight * 2.0 * d * k
            * math.log2(max(num_machines, 2)) * self.pcg_iters
        )
        return sketch + qr + self.pcg_iters * per_pass + network

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """Padded-COO operands, the stacked sketch + its QR workspace,
        one densified chunk slab (transient but live at peak), labels."""
        m = self._resolve_m(int(d) + 1)
        slab = 4.0 * min(self.chunk_rows, n) * d
        return (
            8.0 * n * d * sparsity / num_machines
            + 4.0 * n * k / num_machines
            + 8.0 * m * d
            + slab
        )


class IterativeHessianSketch(LabelEstimator):
    """Iterative Hessian Sketch in input-sparsity time (Pilanci &
    Wainwright; CountSketch per Clarkson & Woodruff).

    Each outer iteration draws a fresh CountSketch (one bucket + one
    sign per row) and makes ONE O(nnz) scatter pass over the COO chunk
    tiles that folds BOTH the sketched rows ``S A`` (flattened 2-D
    scatter-add: segment ``bucket[row]·d + col``, ghost segment for
    inactive lanes) and the exact-gradient operand ``AᵀA X`` — no
    densified slab ever exists, so the pass is priced at scatter rate,
    not slab rate. The step solves the sketched normal equations
    ``(SAᵀSA/n + λI) Δ = −g`` by Cholesky and is GUARDED: a step is
    taken only while the exact gradient norm still shrinks, so a too-
    small sketch degrades to fewer accepted steps, never divergence.

    ``compress="int16_bf16"`` folds over the compressed-resident tier
    (``data/resident.py`` — 4 B/nnz, decode fused into the fold's
    casts), the same storage class the gram engine offers.
    """

    def __init__(
        self,
        lam: float = 0.0,
        sketch_size: Optional[int] = None,
        sketch_factor: int = 4,
        outer_iters: int = 3,
        seed: int = 0,
        chunk_rows: int = 65536,
        num_features: Optional[int] = None,
        compress: Optional[str] = None,
    ):
        if compress not in (None, "int16_bf16"):
            raise ValueError(
                f'compress must be None or "int16_bf16", got {compress!r}'
            )
        self.lam = lam
        self.sketch_size = sketch_size
        self.sketch_factor = sketch_factor
        self.outer_iters = outer_iters
        self.seed = seed
        self.chunk_rows = chunk_rows
        self.num_features = num_features
        self.compress = compress
        from keystone_tpu.ops.learning import cost as cost_mod

        self._cs_overhead = cost_mod.countsketch_overhead()
        self._gather_overhead = cost_mod.sparse_gather_overhead()

    @property
    def weight(self) -> int:
        return self.outer_iters + 1

    def _resolve_m(self, d1: int) -> int:
        return int(self.sketch_size or self.sketch_factor * d1)

    def fit(self, data: Dataset, labels: Dataset):
        from keystone_tpu.ops.sparse import is_sparse_dataset
        from keystone_tpu.ops.learning.linear import (
            LinearMapper, SparseLinearMapper,
        )

        B = jnp.asarray(labels.array).astype(jnp.float32)
        if is_sparse_dataset(data):
            indices = jnp.asarray(data.data["indices"])
            values = jnp.asarray(data.data["values"])
            d = self.num_features or int(jnp.max(indices)) + 1
            idx1, val1 = _append_intercept(indices, values, data.n, d)
            W1 = self._fit_sparse(idx1, val1, B, d + 1, data.n)
            return SparseLinearMapper(W1[:-1], b_opt=W1[-1])
        A = jnp.asarray(data.array).astype(jnp.float32)
        npad = A.shape[0]
        ones = (jnp.arange(npad) < data.n).astype(A.dtype)[:, None]
        A1 = jnp.concatenate([A, ones], axis=1)
        W1 = self._fit_dense(A1, B, data.n)
        return LinearMapper(W1[:-1], b_opt=W1[-1])

    def _fit_sparse(self, idx1, val1, B, d1: int, n: int):
        from keystone_tpu.data.resident import (
            CompressedCOOChunks, raw_chunk_tiles,
        )
        from keystone_tpu.ops.sparse import sparse_matmul_t

        c = min(self.chunk_rows, idx1.shape[0])
        if self.compress == "int16_bf16":
            chunks = CompressedCOOChunks.encode(
                np.asarray(idx1), np.asarray(val1), np.asarray(B),
                chunk_rows=c, d=d1, n_true=n,
            )
            idx_t, val_t, _ = chunks.operands()
        else:
            idx_t, val_t, _ = raw_chunk_tiles(idx1, val1, B, c)
        nchunks = int(idx_t.shape[0])
        m = self._resolve_m(d1)
        k = B.shape[1]
        AtB = sparse_matmul_t(idx1, val1, B, d1)
        key = jax.random.key(self.seed)

        from keystone_tpu.ops import pallas_ops

        # The sketch accumulation has two shapes: a fused Pallas kernel
        # (countsketch_scatter: one-hot sketch tile × densified chunk
        # tile on the MXU, no HBM scatter) when direct dispatch is safe,
        # else the flattened-segment scatter-add. Same algebra; the
        # kernel sums in tiled MXU order so the paths agree to float
        # associativity (pinned in tests/test_pallas_ops.py).
        use_kernel = pallas_ops.pallas_direct_ok(idx_t, val_t)

        def fold_pass(X, key_t):
            """One streamed pass: CountSketch fold + AᵀA X, together."""

            def step(carry, cid):
                SA_acc, AtAX = carry
                idxi = idx_t[cid].astype(jnp.int32)
                valf = val_t[cid].astype(jnp.float32)
                mask = (idxi >= 0) & (idxi < d1)
                safe = jnp.where(mask, idxi, 0)
                vals = jnp.where(mask, valf, 0.0)
                kc = jax.random.fold_in(key_t, cid)
                ks, kb = jax.random.split(kc)
                bucket = jax.random.randint(kb, (c,), 0, m)
                sign = jax.random.rademacher(ks, (c,), dtype=jnp.float32)
                if use_kernel:
                    SA_acc = SA_acc + pallas_ops.countsketch_scatter(
                        jnp.where(mask, idxi, -1), vals, bucket, sign, m, d1
                    )
                else:
                    seg = jnp.where(mask, bucket[:, None] * d1 + safe, m * d1)
                    SA_acc = SA_acc.at[seg.reshape(-1)].add(
                        (sign[:, None] * vals).reshape(-1)
                    )
                # Exact-gradient operand on the same chunk: gather rows
                # of X, then scatter back (ghost row d1 for pad lanes).
                rows = jnp.sum(
                    vals[:, :, None] * jnp.take(X, safe, axis=0), axis=1
                )
                back = jnp.where(mask, safe, d1)
                AtAX = AtAX.at[back.reshape(-1)].add(
                    (vals[:, :, None] * rows[:, None, :]).reshape(-1, X.shape[1])
                )
                return (SA_acc, AtAX), None

            init = (
                jnp.zeros((m, d1), jnp.float32)
                if use_kernel
                else jnp.zeros((m * d1 + 1,), jnp.float32),
                jnp.zeros((d1 + 1, X.shape[1]), jnp.float32),
            )
            (SA_acc, AtAX), _ = jax.lax.scan(
                step, init, jnp.arange(nchunks)
            )
            SA = SA_acc if use_kernel else SA_acc[: m * d1].reshape(m, d1)
            return SA, AtAX[:d1]

        X = jnp.zeros((d1, k), jnp.float32)
        X_prev, prev_gnorm = X, None
        for t in range(self.outer_iters):
            SA, AtAX = fold_pass(X, jax.random.fold_in(key, t))
            g = AtAX / n - AtB / n + self.lam * X
            gnorm = float(jnp.linalg.norm(g))
            if prev_gnorm is not None and gnorm >= prev_gnorm:
                # Roll back the step that RAISED the exact gradient
                # norm — a rank-deficient sketch (m << d) can overshoot
                # through the sketched Hessian's null space, and the
                # returned model must never be worse than an iterate we
                # already held.
                logger.info(
                    "IHS guard: gradient norm %.3g >= %.3g at outer %d; "
                    "rolling back and stopping", gnorm, prev_gnorm, t,
                )
                X = X_prev
                break
            prev_gnorm = gnorm
            X_prev = X
            X = X - self._sketched_newton_step(SA, g, n, d1)
        return X

    def _fit_dense(self, A1, B, n: int):
        d1 = A1.shape[1]
        m = self._resolve_m(d1)
        AtB = A1.T @ B
        key = jax.random.key(self.seed)
        X = jnp.zeros((d1, B.shape[1]), jnp.float32)
        X_prev, prev_gnorm = X, None
        for t in range(self.outer_iters):
            kt = jax.random.fold_in(key, t)
            ks, kb = jax.random.split(kt)
            bucket = jax.random.randint(kb, (A1.shape[0],), 0, m)
            sign = jax.random.rademacher(ks, (A1.shape[0],), dtype=jnp.float32)
            SA = jax.ops.segment_sum(
                A1 * sign[:, None], bucket, num_segments=m
            )
            g = A1.T @ (A1 @ X) / n - AtB / n + self.lam * X
            gnorm = float(jnp.linalg.norm(g))
            if prev_gnorm is not None and gnorm >= prev_gnorm:
                X = X_prev  # same rollback as the sparse path
                break
            prev_gnorm = gnorm
            X_prev = X
            X = X - self._sketched_newton_step(SA, g, n, d1)
        return X

    def _sketched_newton_step(self, SA, g, n: int, d1: int):
        from jax.scipy.linalg import cho_factor, cho_solve

        H = SA.T @ SA / n + (self.lam + _EPS) * jnp.eye(d1, dtype=SA.dtype)
        return cho_solve(cho_factor(H), g)

    def cost(
        self, n, d, k, sparsity, num_machines,
        cpu_weight, mem_weight, network_weight,
        sketch_overhead: Optional[float] = None,
        gather_overhead: Optional[float] = None,
    ) -> float:
        """Per outer: one fused O(nnz) scatter pass (CountSketch fold at
        the scatter rate + the gradient's gather/scatter priced like a
        gather-engine iteration), the sketched gram ``2 m d²`` and its
        ``d³/3`` Cholesky; plus the one-time AᵀB pass."""
        if sketch_overhead is None:
            sketch_overhead = self._cs_overhead
        if gather_overhead is None:
            gather_overhead = self._gather_overhead
        m = self._resolve_m(int(d) + 1)
        nnz = n * sparsity * d
        gather_pass = (
            gather_overhead
            * max(cpu_weight * nnz * k, mem_weight * nnz) / num_machines
        )
        per_outer = (
            sketch_overhead * mem_weight * nnz / num_machines
            + cpu_weight * (2.0 * m * d * d + 2.0 * d ** 3 / 3.0)
            / num_machines
            + gather_pass
        )
        network = (
            network_weight * d * k * self.outer_iters
            * math.log2(max(num_machines, 2))
        )
        return self.outer_iters * per_outer + gather_pass + network

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """COO operands (compressed tier: 4 B/nnz, infeasible past the
        int16 index boundary), the flattened CountSketch accumulator
        (m·d f32 — the dominant term), sketched Gramian + its Cholesky
        copy, labels."""
        if self.compress is not None:
            from keystone_tpu.data import resident as resident_mod

            if not resident_mod.compressible_dim(d + 1):
                return float("inf")
            bytes_per_nnz = resident_mod.COMPRESSED_BYTES_PER_NNZ
        else:
            bytes_per_nnz = 8.0
        m = self._resolve_m(int(d) + 1)
        return (
            bytes_per_nnz * n * d * sparsity / num_machines
            + 4.0 * n * k / num_machines
            + 4.0 * m * d
            + 8.0 * d * d
        )
