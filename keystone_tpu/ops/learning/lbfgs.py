"""Distributed L-BFGS least-squares solvers.

Reference: nodes/learning/LBFGS.scala:14-281 and Gradient.scala:10-123 — a
Breeze LBFGS optimizer driving a cost function whose gradient is computed
per-partition and treeReduce-summed; loss = lossSum/n + ½λ‖W‖².

TPU-native: the full-batch loss+gradient is one jit-compiled sharded
computation (two GEMMs; the reduction over the sharded row axis is an XLA
all-reduce), and the L-BFGS direction/zoom-linesearch updates run on device
via optax's lbfgs (replacing Breeze's optimizer loop).
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from keystone_tpu.data import Dataset
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.learning.linear import LinearMapper
from keystone_tpu.workflow import LabelEstimator

logger = logging.getLogger("keystone_tpu.lbfgs")


def least_squares_loss(W, X, Y, lam: float, n: int):
    """½‖XW − Y‖²/n + ½λ‖W‖² (LBFGS.scala:105-119).

    Padding rows of X and Y are zero, so their residual (0·W − 0) contributes
    nothing; only the divisor uses the true n.
    """
    residual = X @ W - Y
    data_loss = 0.5 * jnp.sum(residual * residual) / n
    return data_loss + 0.5 * lam * jnp.sum(W * W)


def run_lbfgs(
    X,
    Y,
    lam: float = 0.0,
    num_iterations: int = 100,
    convergence_tol: float = 1e-4,
    n: Optional[int] = None,
    W_init=None,
):
    """Minimize the ridge least-squares loss with L-BFGS.

    X: (n_pad, d) row-sharded features; Y: (n_pad, k) labels. Returns (d, k).
    The whole optimization loop (direction, zoom linesearch, convergence test)
    is a single compiled while_loop on device.
    """
    X = jnp.asarray(X)
    Y = jnp.asarray(Y)
    # Mixed-precision inputs (e.g. f32 sparse values + f64 labels) must agree,
    # or the linesearch cond branches trace to different dtypes.
    dtype = jnp.result_type(X.dtype, Y.dtype)
    X = X.astype(dtype)
    Y = Y.astype(dtype)
    n = n or X.shape[0]
    W0 = (
        jnp.asarray(W_init, dtype=dtype)
        if W_init is not None
        else jnp.zeros((X.shape[1], Y.shape[1]), dtype=dtype)
    )

    loss_fn = lambda W: least_squares_loss(W, X, Y, lam, n)
    solver = optax.lbfgs()

    @jax.jit
    def optimize(W0):
        value_and_grad = optax.value_and_grad_from_state(loss_fn)

        def step(carry):
            W, state, _ = carry
            value, grad = value_and_grad(W, state=state)
            updates, state = solver.update(
                grad, state, W, value=value, grad=grad, value_fn=loss_fn
            )
            W = optax.apply_updates(W, updates)
            return W, state, grad

        def cond(carry):
            W, state, grad = carry
            count = optax.tree_utils.tree_get(state, "count")
            gnorm = optax.tree_utils.tree_norm(grad)
            return (count < num_iterations) & (gnorm > convergence_tol)

        state = solver.init(W0)
        grad0 = jax.grad(loss_fn)(W0)
        W, state, _ = jax.lax.while_loop(cond, step, (W0, state, grad0))
        return W, loss_fn(W)

    W, final_loss = optimize(W0)
    logger.info("LBFGS final loss: %s", float(final_loss))
    return W


class DenseLBFGSwithL2(LabelEstimator):
    """Dense-input LBFGS ridge solver with mean-centering intercepts
    (reference: LBFGS.scala:135-192)."""

    def __init__(
        self,
        lam: float = 0.0,
        num_iterations: int = 100,
        convergence_tol: float = 1e-4,
    ):
        self.lam = lam
        self.num_iterations = num_iterations
        self.convergence_tol = convergence_tol

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        feature_scaler = StandardScaler(normalize_std_dev=False).fit(data)
        label_scaler = StandardScaler(normalize_std_dev=False).fit(labels)
        A = jnp.asarray(feature_scaler.batch_apply(data).array)
        B = jnp.asarray(label_scaler.batch_apply(labels).array)
        W = run_lbfgs(
            A, B, lam=self.lam,
            num_iterations=self.num_iterations,
            convergence_tol=self.convergence_tol,
            n=data.n,
        )
        return LinearMapper(W, b_opt=label_scaler.mean, feature_scaler=feature_scaler)

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight, network_weight
    ) -> float:
        """Analytic cost model (LBFGS.scala:175-191)."""
        import math

        flops = n * d * k / num_machines
        bytes_scanned = n * d / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        return self.num_iterations * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


class SparseLBFGSwithL2(LabelEstimator):
    """Sparse-input LBFGS ridge solver (reference: LBFGS.scala:208-281).

    Sparse rows arrive as host dicts/(indices, values) pairs; on TPU the
    gradient GEMMs run on a densified batch (BCOO segment-sum formulations are
    a planned optimization — XLA TPU has no efficient general spmm). The
    append-ones intercept trick of the reference is kept.
    """

    def __init__(
        self,
        lam: float = 0.0,
        num_iterations: int = 100,
        convergence_tol: float = 1e-4,
        num_features: Optional[int] = None,
    ):
        self.lam = lam
        self.num_iterations = num_iterations
        self.convergence_tol = convergence_tol
        self.num_features = num_features

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        from keystone_tpu.ops.sparse import densify_dataset

        A = jnp.asarray(densify_dataset(data, self.num_features).array)
        B = jnp.asarray(labels.array)
        # Append-ones column learns the intercept jointly (LBFGS.scala:208-281).
        npad = A.shape[0]
        ones = (jnp.arange(npad) < data.n).astype(A.dtype)[:, None]
        A1 = jnp.concatenate([A, ones], axis=1)
        W1 = run_lbfgs(
            A1, B, lam=self.lam,
            num_iterations=self.num_iterations,
            convergence_tol=self.convergence_tol,
            n=data.n,
        )
        return LinearMapper(W1[:-1], b_opt=W1[-1])

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight, network_weight,
        sparse_overhead: float = 8.0,
    ) -> float:
        """Analytic cost model (LBFGS.scala:264-280)."""
        import math

        flops = n * sparsity * d * k / num_machines
        bytes_scanned = n * d * sparsity / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        return self.num_iterations * (
            sparse_overhead * max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )
