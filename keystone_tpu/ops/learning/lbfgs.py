"""Distributed L-BFGS least-squares solvers.

Reference: nodes/learning/LBFGS.scala:14-281 and Gradient.scala:10-123 — a
Breeze LBFGS optimizer driving a cost function whose gradient is computed
per-partition and treeReduce-summed; loss = lossSum/n + ½λ‖W‖².

TPU-native: the full-batch loss+gradient is one jit-compiled sharded
computation (two GEMMs; the reduction over the sharded row axis is an XLA
all-reduce), and the whole optimizer loop is one lax.while_loop. Because the
objective is the ridge *quadratic*, no generic linesearch is needed: the
step along the two-loop L-BFGS direction is exact,
``α = −gᵀp / pᵀHp`` with one Hessian-apply ``Hp = Aᵀ(Ap)/n + λp`` per
iteration, and the gradient updates incrementally (``g += α·Hp`` — the
gradient is linear in W). One data pass per iteration total, versus the
several loss/gradient evaluations per zoom-linesearch step a generic
optimizer pays (Breeze's Wolfe search in the reference, LBFGS.scala:87-103).
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.learning.linear import LinearMapper
from keystone_tpu.workflow import LabelEstimator

logger = logging.getLogger("keystone_tpu.lbfgs")


def _matmul(X, P):
    """X @ P where X is a dense array or a padded-COO dict (never densified)."""
    if isinstance(X, dict):
        from keystone_tpu.ops.sparse import sparse_matmul

        return sparse_matmul(X["indices"], X["values"], P)
    return X @ P


def _rmatmul(X, V, d: int):
    """Xᵀ @ V for dense or padded-COO X."""
    if isinstance(X, dict):
        from keystone_tpu.ops.sparse import sparse_matmul_t

        return sparse_matmul_t(X["indices"], X["values"], V, d)
    return X.T @ V


def least_squares_loss(W, X, Y, lam: float, n: int):
    """½‖XW − Y‖²/n + ½λ‖W‖² (LBFGS.scala:105-119).

    Padding rows of X and Y are zero, so their residual (0·W − 0) contributes
    nothing; only the divisor uses the true n. X may be dense or a
    padded-COO dict.
    """
    residual = _matmul(X, W) - Y
    data_loss = 0.5 * jnp.sum(residual * residual) / n
    return data_loss + 0.5 * lam * jnp.sum(W * W)


def run_lbfgs(
    X,
    Y,
    lam: float = 0.0,
    num_iterations: int = 100,
    convergence_tol: float = 1e-4,
    n: Optional[int] = None,
    W_init=None,
):
    """Minimize the ridge least-squares loss with L-BFGS.

    X: (n_pad, d) row-sharded features — a dense array OR a padded-COO dict
    ``{"indices", "values"}`` (sparse input requires ``W_init``, whose row
    count fixes d), in which case every data pass runs
    through the gather/segment-sum sparse kernels and the dense design
    matrix never exists. Y: (n_pad, k) labels. Returns (d, k). The whole
    optimization loop (two-loop direction, exact quadratic step, convergence
    test) is a single compiled while_loop on device.
    """
    Y = jnp.asarray(Y)
    if isinstance(X, dict):
        values = jnp.asarray(X["values"])
        dtype = jnp.result_type(values.dtype, Y.dtype)
        X = {
            "indices": jnp.asarray(X["indices"]),
            "values": values.astype(dtype),
        }
        n_rows = X["indices"].shape[0]
        if W_init is None:
            raise ValueError(
                "sparse run_lbfgs needs W_init (or use SparseLBFGSwithL2, "
                "which sizes the model from num_features)"
            )
    else:
        X = jnp.asarray(X)
        # Mixed-precision inputs (e.g. f32 sparse values + f64 labels) must
        # agree so the while_loop carry has one consistent dtype.
        dtype = jnp.result_type(X.dtype, Y.dtype)
        X = X.astype(dtype)
        n_rows = X.shape[0]
    Y = Y.astype(dtype)
    n = n or n_rows
    W0 = (
        jnp.asarray(W_init, dtype=dtype)
        if W_init is not None
        else jnp.zeros((X.shape[1], Y.shape[1]), dtype=dtype)
    )

    W, final_loss = _lbfgs_core(
        X, Y, W0,
        jnp.asarray(lam, dtype=dtype),
        jnp.asarray(num_iterations),
        jnp.asarray(convergence_tol, dtype=dtype),
        jnp.asarray(n, dtype=dtype),
    )
    logger.info("LBFGS final loss: %s", float(final_loss))
    return W


_LBFGS_HISTORY = 10  # standard L-BFGS memory


def _lbfgs_quad_loop(hvp, AtB, W0, lam, num_iterations, tol):
    """The L-BFGS loop on the ridge quadratic, generic over the Hessian
    apply: ``hvp`` may be the data-pass form Aᵀ(A·)/n + λ· or the
    Gramian form G·/n + λ· — algebraically identical operators, so the
    iterate sequences coincide (up to summation order). Traceable."""
    history = _LBFGS_HISTORY
    dtype = W0.dtype

    def vdot(a, b):
        return jnp.sum(a * b)

    def direction(grad, S, Yh, rho, count):
        """Two-loop recursion over the circular (history, d, k) buffers."""
        m = jnp.minimum(count, history)

        def bwd(i, carry):
            q, alphas = carry
            # i-th most recent pair: slot (count - 1 - i) mod history
            slot = jnp.mod(count - 1 - i, history)
            valid = i < m
            a = jnp.where(valid, rho[slot] * vdot(S[slot], q), 0.0)
            q = q - a * Yh[slot]
            return q, alphas.at[i].set(a)

        q, alphas = jax.lax.fori_loop(
            0, history, bwd, (grad, jnp.zeros((history,), dtype=dtype))
        )
        last = jnp.mod(count - 1, history)
        ys = vdot(S[last], Yh[last])
        yy = vdot(Yh[last], Yh[last])
        # Guard on ys > 0 (not just count): a degenerate zero pair stored
        # after an alpha=0 step must fall back to the steepest-descent
        # scaling, not zero the direction forever.
        gamma = jnp.where(ys > 0, ys / jnp.maximum(yy, 1e-30), 1.0)
        r = gamma * q

        def fwd(j, r):
            i = history - 1 - j  # oldest -> newest
            slot = jnp.mod(count - 1 - i, history)
            valid = i < m
            beta = jnp.where(valid, rho[slot] * vdot(Yh[slot], r), 0.0)
            return r + jnp.where(valid, alphas[i] - beta, 0.0) * S[slot]

        r = jax.lax.fori_loop(0, history, fwd, r)
        return -r

    def step(carry):
        W, grad, S, Yh, rho, count, _ = carry
        p = direction(grad, S, Yh, rho, count)
        Hp = hvp(p)
        denom = vdot(p, Hp)
        alpha = jnp.where(denom > 0, -vdot(grad, p) / denom, 0.0)
        s = alpha * p
        y = alpha * Hp  # grad(W+s) − grad(W) for the quadratic
        W = W + s
        grad = grad + y
        slot = jnp.mod(count, history)
        sy = vdot(s, y)
        S = S.at[slot].set(s)
        Yh = Yh.at[slot].set(y)
        rho = rho.at[slot].set(jnp.where(sy > 0, 1.0 / sy, 0.0))
        return W, grad, S, Yh, rho, count + 1, jnp.linalg.norm(grad)

    def cond(carry):
        _, _, _, _, _, count, gnorm = carry
        return (count < num_iterations) & (gnorm > tol)

    d, k = W0.shape
    grad0 = hvp(W0) - AtB
    S0 = jnp.zeros((history, d, k), dtype=dtype)
    Y0 = jnp.zeros((history, d, k), dtype=dtype)
    rho0 = jnp.zeros((history,), dtype=dtype)
    carry = (W0, grad0, S0, Y0, rho0, 0, jnp.linalg.norm(grad0))
    W, *_ = jax.lax.while_loop(cond, step, carry)
    return W


def _lbfgs_body(X, Y, W0, lam, num_iterations, tol, n):
    """Traceable LBFGS fit body — shared by the jitted core and the
    fit-fusion path (which traces it INSIDE a featurize+fit program)."""
    d = W0.shape[0]

    def hvp(P):
        # H P = Aᵀ(A P)/n + λP — the one data pass per iteration. For
        # padded-COO X this is a gather pass + a segment-sum scatter pass;
        # the dense matrix never exists.
        return _rmatmul(X, _matmul(X, P), d) / n + lam * P

    AtB = _rmatmul(X, Y, d) / n  # constant term of the gradient
    W = _lbfgs_quad_loop(hvp, AtB, W0, lam, num_iterations, tol)
    return W, least_squares_loss(W, X, Y, lam, n)


@jax.jit
def _lbfgs_core(X, Y, W0, lam, num_iterations, tol, n):
    """Module-level jitted core (one executable per shape set, reused across
    fits; hyperparameters are traced scalars so they never trigger
    recompiles)."""
    return _lbfgs_body(X, Y, W0, lam, num_iterations, tol, n)


@jax.jit
def _lbfgs_gram_core(G, AtY, yty, W0, lam, num_iterations, tol, n):
    """L-BFGS on the accumulated normal equations: hvp = G·/n + λ· — the
    same operator as the data-pass core (G = AᵀA), so the iterates match
    the gather path while each iteration costs one (d, d)×(d, k) GEMM
    instead of a full data pass. Used by the streamed sparse tier, where
    G is folded once over (regenerated or resident) chunks."""

    def hvp(P):
        return (
            jnp.dot(G, P, precision=jax.lax.Precision.HIGHEST) / n + lam * P
        )

    W = _lbfgs_quad_loop(hvp, AtY / n, W0, lam, num_iterations, tol)
    # ½‖AW−Y‖²/n + ½λ‖W‖² expanded through G/AtY/yty (no data pass).
    data_loss = 0.5 * (
        jnp.sum(W * jnp.dot(G, W, precision=jax.lax.Precision.HIGHEST))
        - 2.0 * jnp.sum(W * AtY)
        + yty
    ) / n
    return W, data_loss + 0.5 * lam * jnp.sum(W * W)


class DenseLBFGSwithL2(LabelEstimator):
    """Dense-input LBFGS ridge solver with mean-centering intercepts
    (reference: LBFGS.scala:135-192)."""

    def __init__(
        self,
        lam: float = 0.0,
        num_iterations: int = 100,
        convergence_tol: float = 1e-4,
    ):
        self.lam = lam
        self.num_iterations = num_iterations
        self.convergence_tol = convergence_tol

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def device_fit_fn(self):
        """Fit-fusion contract (workflow/fusion.py): mean-centering + the
        whole L-BFGS while_loop as one traceable function, so the
        optimizer compiles upstream featurization INTO the fit — one
        dispatch, the feature matrix never round-trips HBM between
        featurize and solve."""
        from keystone_tpu.ops.stats import StandardScalerModel
        from keystone_tpu.workflow.fusion import DeviceFit, masked_center

        def fit_fn(F, Y, n_true: int, lam):
            Fc, Yc, fmean, ymean = masked_center(F, Y, n_true)
            dtype = jnp.result_type(Fc.dtype, Yc.dtype)
            W0 = jnp.zeros((Fc.shape[1], Yc.shape[1]), dtype=dtype)
            W, _ = _lbfgs_body(
                Fc.astype(dtype), Yc.astype(dtype), W0,
                lam.astype(dtype),
                jnp.asarray(self.num_iterations),
                jnp.asarray(self.convergence_tol, dtype),
                jnp.asarray(n_true, dtype),
            )
            return W, fmean, ymean

        def build(params):
            W, fmean, ymean = params
            return LinearMapper(
                W, b_opt=ymean, feature_scaler=StandardScalerModel(fmean)
            )

        return DeviceFit(
            fit_fn, build,
            operands=(jnp.asarray(self.lam, jnp.float32),),
            program_key=(
                "DenseLBFGS", self.num_iterations, self.convergence_tol,
            ),
        )

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        feature_scaler = StandardScaler(normalize_std_dev=False).fit(data)
        label_scaler = StandardScaler(normalize_std_dev=False).fit(labels)
        A = jnp.asarray(feature_scaler.batch_apply(data).array)
        B = jnp.asarray(label_scaler.batch_apply(labels).array)
        W = run_lbfgs(
            A, B, lam=self.lam,
            num_iterations=self.num_iterations,
            convergence_tol=self.convergence_tol,
            n=data.n,
        )
        return LinearMapper(W, b_opt=label_scaler.mean, feature_scaler=feature_scaler)

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight, network_weight
    ) -> float:
        """Analytic cost model (LBFGS.scala:175-191)."""
        import math

        flops = n * d * k / num_machines
        bytes_scanned = n * d / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        return self.num_iterations * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """Capacity model: the dense matrix plus its centered copy (f32),
        labels twice, and the L-BFGS history pairs (2 x history x d x k)."""
        return (
            8.0 * n * d / num_machines
            + 8.0 * n * k / num_machines
            + 8.0 * _LBFGS_HISTORY * d * k
        )


def _resident_chunk_fn(cid, idx_t, val_t, Y_t):
    """Chunk source slicing pre-tiled resident buffers (module-level so the
    compiled streamed program caches across fits)."""
    return idx_t[cid], val_t[cid], Y_t[cid]


def _fold_stepper(throttle, prefetch_stats):
    """One owner for the per-segment fold step's accounting: transfer +
    fold dispatch + the inflight throttle's blocking, stamped into the
    ``compute`` site of the per-site overlap report
    (``utils.profiling.overlap_report``). Both streamed entry points —
    :func:`run_lbfgs_gram_streamed` and :func:`run_lbfgs_gram_hybrid`
    (which swaps fold programs between its resident and tail legs) —
    fold through this, so the timing/throttle wiring cannot diverge."""
    import time as _time

    from keystone_tpu import obs

    def step(fold, carry, cid0, ops):
        t0 = _time.perf_counter()
        # The fold chunk span (obs plane, ISSUE 9) covers EXACTLY the
        # region the `compute` busy counter covers — transfer + fold
        # dispatch + throttle block — so trace sums and
        # PrefetchStats.site_busy_s agree (tests/test_obs_trace.py).
        # One no-op branch when tracing is off.
        with obs.span("fold.segment", chunk0=int(cid0)):
            carry = fold(
                carry, jnp.asarray(cid0, jnp.int32),
                tuple(jnp.asarray(o) for o in ops),
            )
            throttle.admit(carry[2])
        if prefetch_stats is not None:
            prefetch_stats.add_busy("compute", _time.perf_counter() - t0)
        return carry

    return step


def run_lbfgs_gram_streamed(
    chunk_fn,
    num_chunks: int,
    d: int,
    k: int,
    lam: float = 0.0,
    num_iterations: int = 100,
    convergence_tol: float = 1e-4,
    n: Optional[int] = None,
    use_pallas: bool = False,
    val_dtype=jnp.float32,
    operands=(),
    max_chunks_per_dispatch: Optional[int] = None,
    segment_source=None,
    inflight: int = 2,
    prefetch_depth: int = 2,
    pipeline: bool = True,
    prefetch_stats=None,
    checkpoint=None,
    mesh=None,
    mesh_axis: Optional[str] = None,
):
    """Streamed sparse ridge fit: fold G = AᵀA over COO chunks ONCE
    (``sparse.sparse_gram_stream`` — chunks may be regenerated/loaded per
    call, so the full dataset never exists on device), then run the SAME
    L-BFGS iterates as the gather path against G at one (d, d)×(d, k)
    GEMM per iteration. Returns (W (d, k), final_loss).

    ``operands``: arrays ``chunk_fn`` slices from, passed as
    ``chunk_fn(cid, *operands)``. Resident buffers MUST ride here — a
    chunk_fn that closes over concrete device arrays embeds them as
    program CONSTANTS (hundreds of MB of HLO at Amazon scale, which the
    remote-compile transport rejects outright).

    ``max_chunks_per_dispatch``: bound the fold's program length. By
    default the whole fit is ONE dispatch; very long streams (the full
    n=65e6 Amazon fold is ~1000 chunks ≈ minutes of device time) must be
    segmented or host-side dispatch watchdogs kill the worker (observed).
    Segments reuse one compiled fold program (chunk id is a traced
    operand); chunk ids past ``num_chunks`` in the final ragged segment
    contribute exactly zero.

    ``segment_source``: per-SEGMENT operand loader — the disk-bounded
    tier: neither device HBM nor host RAM ever holds the dataset, only
    ``seg`` chunks at a time. Accepts

      - a :class:`keystone_tpu.data.shards.DiskCOOShards` or its
        prefetchable ``as_source(chunks_per_segment)`` form: segment k+1
        is read from disk on a background thread while segment k's
        transfer + fold are in flight (``prefetch_depth`` bounds staged
        host buffers; 0 reads serially — byte-identical results), or
      - the legacy callable ``segment_source(cid0, seg) -> (idx_t,
        val_t, Y_t)`` (loaded serially: a callable makes no
        thread-safety promise).

    ``chunk_fn`` then receives SEGMENT-RELATIVE ids. Requires
    ``max_chunks_per_dispatch`` (defaulted from a source's
    ``chunks_per_segment``).

    ``inflight``: segments allowed in the device queue before the host
    blocks — keeps dispatch bounded (the tunnel-watchdog constraint the
    old per-segment synchronous drain served) while segment i+1's host
    load and transfer overlap segment i's fold.

    ``pipeline``: double-buffer the densified chunk slab inside the fold
    (``sparse.sparse_gram_fold``) so chunk k+1's regen+densify is
    schedulable against chunk k's accumulating syrk; costs one extra
    resident slab — pass False beside large resident operands.

    ``prefetch_stats``: a :class:`keystone_tpu.data.prefetch.
    PrefetchStats` filled by the prefetched source path (overlap +
    retry/backoff accounting — ``utils.profiling``).

    ``mesh``: a ``jax.sharding.Mesh`` — the multi-chip tier (ISSUE 16).
    The chunk stream partitions CONTIGUOUSLY over ``mesh_axis`` (default
    the ``data`` axis): device j folds chunks ``[j·cpd, (j+1)·cpd)``
    (``cpd = ceil(num_chunks / m)``) into its own local (G, AtY, yty)
    partial — no collective crosses the ICI during the fold — and ONE
    ``lax.psum`` tree reduction of the carry per fit precedes the
    replicated solve. Resident ``operands`` are sharded over their
    leading chunk axis (each device's shard lives in ITS HBM — the
    8-chip form of the compressed-resident tier); a ``segment_source``
    must then be a SEQUENCE of per-device sources whose segment ``s``
    carries device j's segment-relative chunks, read concurrently on
    per-device ``read.d<j>`` lanes (``data/prefetch.py::
    iter_mesh_segments``). ``chunk_fn`` receives device-LOCAL (resident)
    or segment-relative (streamed) ids either way. Checkpointing is not
    supported on the mesh path yet (an explicit ``checkpoint`` raises).

    ``checkpoint``: a :class:`keystone_tpu.data.durable.CheckpointSpec`
    (or directory path; None consults ``KEYSTONE_CHECKPOINT_DIR``)
    snapshotting the (G, AtY, yty) carry + segment cursor every
    ``every_segments`` segments, atomically. A fit killed mid-stream and
    re-run with the same spec resumes from the snapshot BIT-IDENTICALLY
    (tests/test_chaos.py). Requires a segmented fit — an explicit
    checkpoint with the whole fold in one dispatch raises (there is no
    boundary to snapshot at); the env-default spec is simply ignored
    there so a global ``--checkpoint-dir`` drill never breaks
    single-dispatch fits.
    """
    from keystone_tpu.data.durable import (
        fingerprint_token,
        resolve_checkpoint,
        source_fingerprint,
    )

    if n is None:
        raise ValueError("streamed fit needs the true row count n")
    if mesh is not None:
        if checkpoint is not None:
            raise ValueError(
                "mesh-sharded streamed fits do not checkpoint yet: the "
                "carry is a per-device partial on every chip (snapshot "
                "would need a gather); drop checkpoint= or mesh="
            )
        return _run_lbfgs_gram_streamed_mesh(
            chunk_fn, int(num_chunks), int(d), int(k), mesh,
            mesh_axis=mesh_axis, lam=lam, num_iterations=num_iterations,
            convergence_tol=convergence_tol, n=n, use_pallas=use_pallas,
            val_dtype=val_dtype, operands=operands,
            max_chunks_per_dispatch=max_chunks_per_dispatch,
            segment_sources=segment_source, inflight=inflight,
            prefetch_depth=prefetch_depth, prefetch_stats=prefetch_stats,
        )
    explicit_checkpoint = checkpoint is not None
    checkpoint = resolve_checkpoint(checkpoint)
    seg = max_chunks_per_dispatch
    source = None
    if segment_source is not None and not callable(segment_source):
        from keystone_tpu.data.prefetch import COOShardSource, is_shard_source

        if is_shard_source(segment_source):
            source = segment_source
        elif hasattr(segment_source, "segment_source"):
            # A DiskCOOShards-like object: group chunks into segments.
            source = COOShardSource(
                segment_source, seg if seg else min(int(num_chunks), 8)
            )
        else:
            raise TypeError(
                f"segment_source must be callable, a ShardSource, or "
                f"have .segment_source; got {type(segment_source).__name__}"
            )
        if seg is None:
            seg = source.chunks_per_segment
        elif seg != source.chunks_per_segment:
            raise ValueError(
                f"max_chunks_per_dispatch {seg} != the source's "
                f"chunks_per_segment {source.chunks_per_segment}"
            )
    if segment_source is None and (seg is None or seg >= num_chunks):
        if explicit_checkpoint:
            raise ValueError(
                "checkpointing needs a segmented fit: pass "
                "max_chunks_per_dispatch (or a segment_source) so there "
                "are fold boundaries to snapshot at"
            )
        program = _gram_streamed_program(
            chunk_fn, int(num_chunks), int(d), int(k), float(lam),
            int(num_iterations), float(convergence_tol), int(n),
            bool(use_pallas), jnp.dtype(val_dtype), bool(pipeline),
        )
        return program(tuple(operands))

    from keystone_tpu.ops.sparse import sparse_gram_init
    from keystone_tpu.parallel.streaming import BoundedInflight

    if segment_source is not None:
        if seg is None:
            raise ValueError("segment_source requires max_chunks_per_dispatch")
        fold = _gram_fold_program_rel(
            chunk_fn, int(num_chunks), int(d), int(k), int(seg),
            bool(use_pallas), jnp.dtype(val_dtype), bool(pipeline),
        )
    else:
        fold = _gram_fold_program(
            chunk_fn, int(num_chunks), int(d), int(k), int(seg),
            bool(use_pallas), jnp.dtype(val_dtype), bool(pipeline),
        )
    solve = _gram_solve_program(
        int(d), int(k), float(lam), int(num_iterations),
        float(convergence_tol), int(n), jnp.dtype(val_dtype),
    )
    num_segs = -(-int(num_chunks) // int(seg))
    carry = None
    start_seg = 0
    fingerprint = None
    if checkpoint is not None:
        # Geometry + fold-program identity (chunk_fn, dtype/engine
        # flags, operand shapes) + source identity — a stale snapshot
        # from a different chunk source must never seed this fold.
        # Resident operands are fingerprinted by shape/dtype only (a
        # content digest would transfer the dataset host-side); disk
        # sources carry a free content digest via their recorded
        # checksums.
        fingerprint = {
            "kind": "coo_gram_segments", "num_chunks": int(num_chunks),
            "d": int(d), "k": int(k), "seg": int(seg), "n": int(n),
            "val_dtype": str(jnp.dtype(val_dtype)),
            "use_pallas": bool(use_pallas), "pipeline": bool(pipeline),
            "chunk_fn": fingerprint_token(chunk_fn),
            "operands": [
                {"shape": [int(v) for v in getattr(o, "shape", ())],
                 "dtype": str(getattr(o, "dtype", "?"))}
                for o in operands
            ],
            "source": source_fingerprint(
                source if source is not None else segment_source
            ),
        }
        arrays, start_seg = checkpoint.restore(fingerprint)
        if arrays is not None:
            carry = tuple(jnp.asarray(a) for a in arrays)
    if carry is None:
        carry = sparse_gram_init(d, k, val_dtype)
    throttle = BoundedInflight(inflight)
    step = _fold_stepper(throttle, prefetch_stats)

    def folded(cid0, ops):
        nonlocal carry
        carry = step(fold, carry, cid0, ops)

    def maybe_snapshot(s):
        if checkpoint is not None:
            checkpoint.maybe_save(carry, s, num_segs, fingerprint,
                                  stats=prefetch_stats)

    def finish():
        result = solve(carry)
        if checkpoint is not None:
            checkpoint.clear(fingerprint)  # this fit's snapshot only
        return result

    if source is not None:
        from keystone_tpu.data.prefetch import iter_segments

        for s, ops in iter_segments(
            source, prefetch_depth=prefetch_depth, stats=prefetch_stats,
            start=start_seg,
        ):
            folded(s * int(seg), ops)
            maybe_snapshot(s)
        return finish()
    for s in range(start_seg, num_segs):
        cid0 = s * int(seg)
        if segment_source is not None:
            ops = segment_source(int(cid0), int(seg))
        else:
            ops = operands
        folded(cid0, ops)
        maybe_snapshot(s)
    return finish()


def run_lbfgs_gram_hybrid(
    resident_chunk_fn,
    num_resident_chunks: int,
    resident_operands,
    num_chunks: int,
    d: int,
    k: int,
    *,
    lam: float = 0.0,
    num_iterations: int = 100,
    convergence_tol: float = 1e-4,
    n: Optional[int] = None,
    use_pallas: bool = False,
    val_dtype=jnp.float32,
    max_chunks_per_dispatch: int = 8,
    chunk_fn=None,
    segment_source=None,
    prefetch_depth: int = 2,
    prefetch_stats=None,
    pipeline: bool = True,
    inflight: int = 2,
):
    """Hybrid resident+streamed sparse gram fit — the compressed tier's
    full-working-set form (ISSUE 8): chunks ``[0, num_resident_chunks)``
    fold from device-RESIDENT operands (the int16+bf16 compressed COO of
    ``data/resident.py`` — ``resident_chunk_fn(cid, *operands)`` slices
    them; ``pipeline=False`` for this leg, since there is no regen work
    to overlap and no slab headroom beside the resident buffers), and
    chunks ``[num_resident_chunks, num_chunks)`` — the part that truly
    cannot fit — stream exactly as in :func:`run_lbfgs_gram_streamed`:
    either ``chunk_fn(cid)`` regenerated per scan step, or a
    ``segment_source`` ShardSource whose segment ``s`` carries the
    SEGMENT-RELATIVE operands for chunks ``num_resident_chunks +
    [s·seg, (s+1)·seg)``, read ahead on the data-plane runtime
    (``prefetch_depth``; ``prefetch_stats`` collects the per-site
    overlap accounting). One solve runs on the combined G.

    Bit-identity contract: same chunk order, same per-chunk densify +
    fold arithmetic, same carry — the result equals a single streamed
    fit over all ``num_chunks`` chunks with the same ``val_dtype`` and
    per-leg pipeline flags (tests/test_resident.py pins it).
    """
    if n is None:
        raise ValueError("hybrid streamed fit needs the true row count n")
    if num_resident_chunks > num_chunks:
        raise ValueError(
            f"num_resident_chunks {num_resident_chunks} > num_chunks "
            f"{num_chunks}"
        )
    from keystone_tpu.data.prefetch import is_shard_source, iter_segments
    from keystone_tpu.ops.sparse import sparse_gram_init
    from keystone_tpu.parallel.streaming import BoundedInflight

    seg = int(max_chunks_per_dispatch)
    carry = sparse_gram_init(d, k, val_dtype)
    throttle = BoundedInflight(inflight)
    step = _fold_stepper(throttle, prefetch_stats)

    def folded(fold, cid0, ops):
        nonlocal carry
        carry = step(fold, carry, cid0, ops)

    if num_resident_chunks:
        # Phantom ids in a ragged final resident segment are masked dead
        # (live = cid < num_resident_chunks); the SAME chunk ids then
        # fold live through the streamed tail — no chunk is ever folded
        # twice or skipped.
        fold_res = _gram_fold_program(
            resident_chunk_fn, int(num_resident_chunks), int(d), int(k),
            seg, bool(use_pallas), jnp.dtype(val_dtype), False,
        )
        ops_res = tuple(jnp.asarray(o) for o in resident_operands)
        for cid0 in range(0, int(num_resident_chunks), seg):
            folded(fold_res, cid0, ops_res)

    tail = int(num_chunks) - int(num_resident_chunks)
    if tail > 0:
        if segment_source is not None:
            if not is_shard_source(segment_source):
                raise TypeError(
                    "hybrid segment_source must be a ShardSource whose "
                    f"segments carry {seg} segment-relative chunks; got "
                    f"{type(segment_source).__name__}"
                )
            if chunk_fn is None:
                chunk_fn = _resident_chunk_fn
            fold_tail = _gram_fold_program_rel(
                chunk_fn, int(num_chunks), int(d), int(k), seg,
                bool(use_pallas), jnp.dtype(val_dtype), bool(pipeline),
            )
            for s, ops in iter_segments(
                segment_source, prefetch_depth=prefetch_depth,
                stats=prefetch_stats,
            ):
                folded(fold_tail, int(num_resident_chunks) + s * seg, ops)
        else:
            if chunk_fn is None:
                raise ValueError(
                    "a streamed tail needs chunk_fn or segment_source"
                )
            fold_tail = _gram_fold_program(
                chunk_fn, int(num_chunks), int(d), int(k), seg,
                bool(use_pallas), jnp.dtype(val_dtype), bool(pipeline),
            )
            for cid0 in range(int(num_resident_chunks), int(num_chunks),
                              seg):
                folded(fold_tail, cid0, ())

    solve = _gram_solve_program(
        int(d), int(k), float(lam), int(num_iterations),
        float(convergence_tol), int(n), jnp.dtype(val_dtype),
    )
    return solve(carry)


@functools.lru_cache(maxsize=16)
def _gram_fold_program(chunk_fn, num_chunks, d, k, seg, use_pallas,
                       val_dtype, pipeline=True):
    """Compiled fold of ``seg`` consecutive chunks into the (G, AtY, yty)
    carry; the starting chunk id is a traced operand so every segment —
    including the phantom-padded final one — reuses this one executable.
    The carry is donated (G is ~1.2 GB at Amazon geometry)."""
    from keystone_tpu.ops.sparse import sparse_gram_fold

    @functools.partial(jax.jit, donate_argnums=(0,))
    def fold(carry, cid0, operands):
        def cf(cid):
            indices, values, Yc = chunk_fn(cid, *operands)
            live = cid < num_chunks
            return (
                indices,
                jnp.where(live, values, jnp.zeros_like(values)),
                jnp.where(live, Yc, jnp.zeros_like(Yc)),
            )

        return sparse_gram_fold(
            carry, cid0 + jnp.arange(seg), cf, d, k,
            use_pallas=use_pallas, val_dtype=val_dtype, pipeline=pipeline,
        )

    return fold


@functools.lru_cache(maxsize=16)
def _gram_fold_program_rel(chunk_fn, num_chunks, d, k, seg, use_pallas,
                           val_dtype, pipeline=True):
    """Segment fold over SEGMENT-RELATIVE chunk ids: operands hold only
    this segment's ``seg`` chunks (a disk-backed loader's slice), so
    ``chunk_fn`` slices by rel id while liveness masks by the absolute
    id ``cid0 + rel``."""
    from keystone_tpu.ops.sparse import sparse_gram_fold

    @functools.partial(jax.jit, donate_argnums=(0,))
    def fold(carry, cid0, operands):
        def cf(rel):
            indices, values, Yc = chunk_fn(rel, *operands)
            live = (cid0 + rel) < num_chunks
            return (
                indices,
                jnp.where(live, values, jnp.zeros_like(values)),
                jnp.where(live, Yc, jnp.zeros_like(Yc)),
            )

        return sparse_gram_fold(
            carry, jnp.arange(seg), cf, d, k,
            use_pallas=use_pallas, val_dtype=val_dtype, pipeline=pipeline,
        )

    return fold


@functools.lru_cache(maxsize=16)
def _gram_solve_program(d, k, lam, num_iterations, convergence_tol, n,
                        val_dtype):
    """Compiled finalize + L-BFGS-on-G tail of the segmented fold."""
    from keystone_tpu.ops.sparse import gram_finalize, gram_pad_dim

    d_pad = gram_pad_dim(d, val_dtype)

    @jax.jit
    def solve(carry):
        G, AtY, yty = carry
        W, loss = _lbfgs_gram_core(
            gram_finalize(G), AtY, yty,
            jnp.zeros((d_pad, k), jnp.float32),
            jnp.asarray(lam, jnp.float32),
            jnp.asarray(num_iterations),
            jnp.asarray(convergence_tol, jnp.float32),
            jnp.asarray(n, jnp.float32),
        )
        return W[:d], loss

    return solve


def _mesh_fold_axis(mesh, mesh_axis: Optional[str]) -> str:
    """Resolve (and validate) the fold's data-parallel mesh axis."""
    from keystone_tpu.parallel import mesh as mesh_lib

    axis = mesh_axis or mesh_lib.DATA_AXIS
    if axis not in mesh.shape:
        raise ValueError(
            f"mesh {dict(mesh.shape)} has no {axis!r} axis to shard the "
            f"chunk stream over"
        )
    return axis


def _mesh_gram_init(d, k, val_dtype, mesh, axis):
    """Per-device zero carries: stacked (m, ...) arrays sharded over
    ``axis`` so device j's partial lives only in device j's HBM."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from keystone_tpu.ops.sparse import gram_pad_dim

    m = int(mesh.shape[axis])
    d_pad = gram_pad_dim(d, val_dtype)
    sharding = NamedSharding(mesh, P(axis))

    def put(*shape):
        return jax.device_put(np.zeros(shape, np.float32), sharding)

    return (put(m, d_pad, d_pad), put(m, d_pad, k), put(m))


@functools.lru_cache(maxsize=8)
def _gram_fold_program_mesh(chunk_fn, num_chunks, d, k, seg, use_pallas,
                            val_dtype, pipeline, mesh, axis,
                            segment_relative):
    """Mesh-sharded segment fold: each device folds ``seg`` chunks of ITS
    contiguous chunk shard into ITS local (G, AtY, yty) partial. NO
    collective runs here — the single per-fit psum lives in
    :func:`_gram_mesh_solve_program` — so every dispatched step is pure
    device-local syrk work and scaling is bounded only by the one final
    tree reduction.

    Chunk ownership is contiguous: device j owns local ids [0, cpd)
    mapping to global chunks ``j·cpd + local`` (``cpd =
    ceil(num_chunks / m)``); phantom ids past a device's ragged tail are
    masked dead, so no chunk is folded twice or skipped
    (tests/test_multichip.py pins parity with the 1-device fold).
    ``segment_relative``: operands hold only this dispatch's ``seg``
    chunks, stacked (m, seg, ...) and sharded — the per-device-lane
    streamed ingestion path; otherwise operands are the full resident
    shard (leading dim m·cpd, sharded) and ``chunk_fn`` slices by the
    device-local id.
    """
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.ops.sparse import sparse_gram_fold
    from keystone_tpu.parallel import mesh as mesh_lib

    m = int(mesh.shape[axis])
    cpd = -(-int(num_chunks) // m)

    def local(carry, cid0, operands):
        if segment_relative:
            operands = tuple(o[0] for o in operands)
        base = jax.lax.axis_index(axis) * cpd

        def cf(loc):
            sl = loc - cid0 if segment_relative else loc
            indices, values, Yc = chunk_fn(sl, *operands)
            live = (loc < cpd) & (base + loc < num_chunks)
            return (
                indices,
                jnp.where(live, values, jnp.zeros_like(values)),
                jnp.where(live, Yc, jnp.zeros_like(Yc)),
            )

        G, AtY, yty = sparse_gram_fold(
            (carry[0][0], carry[1][0], carry[2][0]),
            cid0 + jnp.arange(seg), cf, d, k,
            use_pallas=use_pallas, val_dtype=val_dtype, pipeline=pipeline,
        )
        return G[None], AtY[None], yty[None]

    sharded = P(axis)
    fold = mesh_lib.shard_map(
        local,
        mesh=mesh,
        in_specs=((sharded, sharded, sharded), P(), sharded),
        out_specs=(sharded, sharded, sharded),
        check_vma=False,
    )
    return functools.partial(jax.jit, donate_argnums=(0,))(fold)


@functools.lru_cache(maxsize=8)
def _gram_mesh_solve_program(d, k, lam, num_iterations, convergence_tol, n,
                             val_dtype, mesh, axis):
    """The fit's ONE cross-device collective: ``lax.psum`` of the
    (G, AtY, yty) pytree over ``axis`` (a pytree psum lowers to a single
    fused all-reduce over the ICI), replicated out, then the standard
    finalize + L-BFGS-on-G solve — identical iterates to the 1-device
    fold up to the reduction's float reassociation."""
    from jax.sharding import PartitionSpec as P

    from keystone_tpu.parallel import mesh as mesh_lib

    def local(G, AtY, yty):
        return jax.lax.psum((G[0], AtY[0], yty[0]), axis)

    reduce = mesh_lib.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    solve = _gram_solve_program(
        d, k, lam, num_iterations, convergence_tol, n, val_dtype
    )

    def run(carry):
        return solve(reduce(*carry))

    return run


def _run_lbfgs_gram_streamed_mesh(
    chunk_fn, num_chunks, d, k, mesh, *, mesh_axis, lam, num_iterations,
    convergence_tol, n, use_pallas, val_dtype, operands,
    max_chunks_per_dispatch, segment_sources, inflight, prefetch_depth,
    prefetch_stats,
):
    """Mesh driver for :func:`run_lbfgs_gram_streamed` (ISSUE 16): the
    host loop dispatches one shard_map fold per LOCAL segment (all
    devices fold their own shard inside it), throttles inflight
    dispatches, and barriers per step on the CPU backend
    (``mesh_lib.sync_if_cpu`` — the forced-host multi-device queue
    deadlock guard); one psum + replicated solve finish the fit."""
    import time as _time

    from keystone_tpu import obs
    from keystone_tpu.parallel import mesh as mesh_lib
    from keystone_tpu.parallel.streaming import BoundedInflight

    axis = _mesh_fold_axis(mesh, mesh_axis)
    m = int(mesh.shape[axis])
    cpd = -(-int(num_chunks) // m)
    throttle = BoundedInflight(inflight)
    dev_tag = f"{axis}[0-{m - 1}]"

    def step(fold, carry, cid0, ops):
        t0 = _time.perf_counter()
        # The mesh fold is ONE dispatch covering every device's shard;
        # the span carries the device-group tag (satellite: per-device
        # occupancy) and the same compute-site accounting as the
        # single-device stepper.
        with obs.span("fold.segment", chunk0=int(cid0), device=dev_tag,
                      num_devices=m):
            carry = fold(
                carry, jnp.asarray(cid0, jnp.int32),
                tuple(jnp.asarray(o) for o in ops),
            )
            throttle.admit(jnp.sum(carry[2]))
            mesh_lib.sync_if_cpu(carry[2])
        if prefetch_stats is not None:
            prefetch_stats.add_busy("compute", _time.perf_counter() - t0)
        return carry

    carry = _mesh_gram_init(d, k, val_dtype, mesh, axis)
    solve = _gram_mesh_solve_program(
        int(d), int(k), float(lam), int(num_iterations),
        float(convergence_tol), int(n), jnp.dtype(val_dtype), mesh, axis,
    )

    if segment_sources is not None:
        from keystone_tpu.data.prefetch import iter_mesh_segments

        seg = max_chunks_per_dispatch
        sources = list(segment_sources)
        if len(sources) != m:
            raise ValueError(
                f"mesh fold over {axis}={m} needs {m} per-device segment "
                f"sources, got {len(sources)}"
            )
        if seg is None:
            raise ValueError(
                "mesh segment sources need max_chunks_per_dispatch (the "
                "per-device chunks carried by one segment)"
            )
        fold = _gram_fold_program_mesh(
            chunk_fn, int(num_chunks), int(d), int(k), int(seg),
            bool(use_pallas), jnp.dtype(val_dtype), bool(pipeline_ok(seg)),
            mesh, axis, True,
        )
        for s, payloads in iter_mesh_segments(
            sources, prefetch_depth=prefetch_depth, stats=prefetch_stats,
        ):
            # Stack device payloads host-side; device_put inside the fold
            # call shards the (m, seg, ...) stack so each lane's bytes
            # land only on its device.
            ops = tuple(
                np.stack([p[i] for p in payloads])
                for i in range(len(payloads[0]))
            )
            carry = step(fold, carry, s * int(seg), ops)
        return solve(carry)

    # Resident path: pad the chunk axis to m·cpd and shard it so each
    # device holds exactly its contiguous shard (8-chip chip-residency).
    seg = int(max_chunks_per_dispatch) if max_chunks_per_dispatch else cpd
    seg = min(seg, cpd)
    ops = []
    for o in operands:
        o = np.asarray(o)
        pad = m * cpd - o.shape[0]
        if pad:
            fill = -1 if np.issubdtype(o.dtype, np.integer) else 0
            o = np.pad(
                o, [(0, pad)] + [(0, 0)] * (o.ndim - 1),
                constant_values=fill,
            )
        ops.append(mesh_lib.shard_rows(o, mesh, axis=axis))
    ops = tuple(ops)
    fold = _gram_fold_program_mesh(
        chunk_fn, int(num_chunks), int(d), int(k), seg, bool(use_pallas),
        jnp.dtype(val_dtype), False, mesh, axis, False,
    )
    for cid0 in range(0, cpd, seg):
        carry = step(fold, carry, cid0, ops)
    return solve(carry)


def pipeline_ok(seg: int) -> bool:
    """Streamed mesh segments double-buffer only when there is more than
    one chunk to overlap inside a dispatch."""
    return int(seg) > 1


@functools.lru_cache(maxsize=16)
def _gram_streamed_program(chunk_fn, num_chunks, d, k, lam, num_iterations,
                           convergence_tol, n, use_pallas, val_dtype,
                           pipeline=True):
    """Compiled streamed-fit program, cached per (chunk_fn identity, fit
    geometry). Building the jit inside every call would make EVERY fit —
    including the timed second run of a warm benchmark — retrace and
    recompile the whole chunk scan (~30 s at Amazon geometry). Callers
    therefore pass a STABLE chunk_fn (module-level function or one object
    reused across fits), with per-fit arrays in ``operands``."""
    from keystone_tpu.ops.sparse import gram_pad_dim, sparse_gram_stream

    d_pad = gram_pad_dim(d, val_dtype)

    @jax.jit
    def _run(operands):
        def cf(cid):
            return chunk_fn(cid, *operands)

        G, AtY, yty = sparse_gram_stream(
            cf, num_chunks, d, k, use_pallas=use_pallas,
            val_dtype=val_dtype, pipeline=pipeline,
        )
        # Solve at the padded width: padded rows of AtY are zero and G's
        # padded rows/cols are zero, so those W rows stay exactly zero
        # through every iterate (pure-λ ridge on a zero gradient).
        W, loss = _lbfgs_gram_core(
            G, AtY, yty, jnp.zeros((d_pad, k), jnp.float32),
            jnp.asarray(lam, jnp.float32),
            jnp.asarray(num_iterations),
            jnp.asarray(convergence_tol, jnp.float32),
            jnp.asarray(n, jnp.float32),
        )
        return W[:d], loss

    return _run


class SparseLBFGSwithL2(LabelEstimator):
    """Sparse-input LBFGS ridge solver (reference: LBFGS.scala:208-281).

    Padded-COO input datasets run the whole optimization through the sparse
    gather/segment-sum kernels (the TPU form of the reference's active-index
    gradient loops, Gradient.scala:58-123) — the dense design matrix never
    exists, so Amazon-scale problems (n·d ≈ 1e12 dense elements at
    sparsity 0.005) fit in HBM. The append-ones intercept trick of the
    reference is kept: every row gets one extra active index at column d
    with value 1. Dense input datasets take the ordinary dense core.

    ``solver`` picks the iteration engine for sparse input:
      - "gather" (default, the reference-shaped path): every L-BFGS
        iteration is a gather + segment-sum data pass — bounded by the
        chip's random-access rate (~2e8 idx/s).
      - "gram": fold G = AᵀA once over densified row chunks (MXU syrk,
        ``sparse.sparse_gram_stream``), then run the SAME iterates against
        G at one small GEMM per iteration. ~10x faster end-to-end at
        Amazon geometry when iterations > ~2, at the cost of a (d_pad)²
        f32 Gramian in HBM — prefer it whenever d ≲ 40k.

    ``compress`` (gram solver only) selects the COMPRESSED-RESIDENT
    storage class (``data/resident.py``, ISSUE 8): ``"int16_bf16"``
    encodes the padded-COO operands at 4 bytes/nnz (int16 index + bf16
    value) before the fold, with the decode fused into the fold's
    densify casts — the same iterates as ``gram_dtype="bf16"`` (the
    fold quantizes values to bf16 either way, so results are
    bit-identical), at HALF the resident operand. This is a capacity
    play: the cost model prices it as a third tier between HBM-raw and
    disk, so working sets that bust HBM raw but fit compressed stay
    chip-resident with no flag. Requires every index (including the
    intercept lane at d) to fit int16 — encode raises at the overflow
    boundary rather than ever wrapping.
    """

    def __init__(
        self,
        lam: float = 0.0,
        num_iterations: int = 100,
        convergence_tol: float = 1e-4,
        num_features: Optional[int] = None,
        solver: str = "gather",
        gram_chunk_rows: int = 65536,
        gram_dtype: Optional[str] = None,
        compress: Optional[str] = None,
    ):
        if solver not in ("gather", "gram"):
            raise ValueError(f'solver must be "gather" or "gram", got {solver!r}')
        if gram_dtype not in (None, "f32", "bf16"):
            raise ValueError(
                f'gram_dtype must be None, "f32" or "bf16", got {gram_dtype!r}'
            )
        if compress not in (None, "int16_bf16"):
            raise ValueError(
                f'compress must be None or "int16_bf16", got {compress!r}'
            )
        if compress is not None and solver != "gram":
            raise ValueError(
                'compress requires solver="gram" (the gather engine reads '
                "COO lanes directly and has no densify to fuse the decode "
                "into)"
            )
        if compress is not None and gram_dtype == "f32":
            raise ValueError(
                'compress="int16_bf16" stores bf16 values — an exact-f32 '
                "fold over them would be paying full precision for "
                "already-quantized data; drop one of the two"
            )
        self.lam = lam
        self.num_iterations = num_iterations
        self.convergence_tol = convergence_tol
        self.num_features = num_features
        self.solver = solver
        self.compress = compress
        self.gram_chunk_rows = gram_chunk_rows
        # Densified-slab dtype for the gram fold. None follows the input
        # values' dtype; "bf16" folds f32 inputs through bf16 slabs — the
        # MXU-native single-pass recipe (~6x the 6-pass f32 syrk), at the
        # cost of bf16-quantizing the DATA inside the fold (G error ~0.4%
        # relative — the iterates shift by the same order; quantified in
        # tests/test_sparse_gram.py).
        self.gram_dtype = gram_dtype
        # Resolved at CONSTRUCTION like the selector's cpu/mem/network
        # weights (cost.py) — a mid-process KEYSTONE_COST_WEIGHTS flip
        # must not mix weight families within one estimator's ranking.
        from keystone_tpu.ops.learning import cost as cost_mod

        self._sparse_overhead = cost_mod.sparse_gather_overhead()

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def fit(self, data: Dataset, labels: Dataset):
        from keystone_tpu.ops.sparse import is_sparse_dataset
        from keystone_tpu.ops.learning.linear import SparseLinearMapper

        B = jnp.asarray(labels.array)
        if is_sparse_dataset(data):
            indices = jnp.asarray(data.data["indices"])
            values = jnp.asarray(data.data["values"])
            d = self.num_features or int(jnp.max(indices)) + 1
            npad = indices.shape[0]
            # Append-ones column at index d learns the intercept jointly
            # (LBFGS.scala:208-281); padding rows get an inactive (−1) lane.
            valid = jnp.arange(npad) < data.n
            idx1 = jnp.concatenate(
                [indices, jnp.where(valid, d, -1)[:, None].astype(indices.dtype)],
                axis=1,
            )
            val1 = jnp.concatenate(
                [values, valid.astype(values.dtype)[:, None]], axis=1
            )
            if self.solver == "gram":
                W1 = self._fit_gram(idx1, val1, B, d + 1, data.n)
            else:
                dtype = jnp.result_type(values.dtype, B.dtype)
                W1 = run_lbfgs(
                    {"indices": idx1, "values": val1}, B, lam=self.lam,
                    num_iterations=self.num_iterations,
                    convergence_tol=self.convergence_tol,
                    n=data.n,
                    W_init=jnp.zeros((d + 1, B.shape[1]), dtype=dtype),
                )
            return SparseLinearMapper(W1[:-1], b_opt=W1[-1])

        A = jnp.asarray(data.array)
        npad = A.shape[0]
        ones = (jnp.arange(npad) < data.n).astype(A.dtype)[:, None]
        A1 = jnp.concatenate([A, ones], axis=1)
        W1 = run_lbfgs(
            A1, B, lam=self.lam,
            num_iterations=self.num_iterations,
            convergence_tol=self.convergence_tol,
            n=data.n,
        )
        return LinearMapper(W1[:-1], b_opt=W1[-1])

    def _fit_gram(self, idx1, val1, B, d1: int, n: int):
        """Gram-engine fit over RESIDENT padded-COO buffers: pre-chunk the
        rows host-side (padding chunks with inactive lanes), fold G once,
        iterate on it. With ``compress="int16_bf16"`` the operands are
        encoded through the compressed-resident tier
        (``data/resident.py``) first — 4 bytes/nnz resident, decode
        fused into the fold's densify casts."""
        c = min(self.gram_chunk_rows, idx1.shape[0])
        if self.compress == "int16_bf16":
            from keystone_tpu.data.resident import CompressedCOOChunks

            chunks = CompressedCOOChunks.encode(
                np.asarray(idx1), np.asarray(val1), np.asarray(B),
                chunk_rows=c, d=d1, n_true=n,
            )
            idx_t, val_t, Y_t = chunks.operands()
            nchunks = chunks.num_chunks
        else:
            from keystone_tpu.data.resident import raw_chunk_tiles

            idx_t, val_t, Y_t = raw_chunk_tiles(idx1, val1, B, c)
            nchunks = int(idx_t.shape[0])

        from keystone_tpu.ops import pallas_ops

        if self.gram_dtype == "f32":
            # Explicit f32 wins even over bf16-compressed values: the
            # slabs upcast losslessly and the syrk runs the exact 6-pass
            # recipe (the caller is paying for precision on purpose).
            val_dtype = jnp.float32
        elif (
            self.compress is not None
            or self.gram_dtype == "bf16"
            or val1.dtype == jnp.bfloat16
        ):
            val_dtype = jnp.bfloat16
        else:
            val_dtype = jnp.float32
        W, final_loss = run_lbfgs_gram_streamed(
            _resident_chunk_fn,  # stable identity -> compiled-program reuse
            nchunks, d1, B.shape[1],
            lam=self.lam, num_iterations=self.num_iterations,
            convergence_tol=self.convergence_tol, n=n,
            use_pallas=pallas_ops.pallas_direct_ok(val_t),
            val_dtype=val_dtype,
            operands=(idx_t, val_t, Y_t),
            # Resident operands already hold the whole dataset: the
            # double-buffered second slab would be pure extra HBM beside
            # them (the measured resident-capacity cliff sits at n=30e6 /
            # 9.8 GB — bench.py's probe), and there is no regen work to
            # overlap — chunks are slices of the resident buffers.
            pipeline=False,
        )
        logger.info("LBFGS(gram) final loss: %s", float(final_loss))
        return W

    # Measured on-chip calibration (BENCH_r04 amazon row): the gram
    # engine's one-time densify+syrk fold plus 20 G-space iterations cost
    # ~4.5 gather-engine iterations end-to-end at the Amazon geometry —
    # the MXU-vs-random-access gap the reference's CPU-fitted weights
    # cannot express analytically.
    _GRAM_FOLD_ITER_EQUIV = 4.5

    def cost(
        self, n, d, k, sparsity, num_machines, cpu_weight, mem_weight, network_weight,
        sparse_overhead: Optional[float] = None,
    ) -> float:
        """Analytic cost model (LBFGS.scala:264-280). The ``gram`` engine
        is priced as a measured iteration-equivalent of the gather engine
        (fold once, then data-free iterations) — see _GRAM_FOLD_ITER_EQUIV.
        ``sparse_overhead`` (the gather engine's random-access multiplier
        on the sequential mem rate) defaults from the weight family active
        at CONSTRUCTION (cost.sparse_gather_overhead): 500 for the TPU
        weights — measured 2.1e8 random cells/s vs the sequential-scan
        rate on the amazon bench row — 8 for the reference's EC2 set."""
        import math

        if sparse_overhead is None:
            # getattr: instances unpickled from pre-round-6 saves lack the
            # construction-time attribute — resolve from the env then.
            sparse_overhead = getattr(self, "_sparse_overhead", None)
        if sparse_overhead is None:
            from keystone_tpu.ops.learning import cost as cost_mod

            sparse_overhead = cost_mod.sparse_gather_overhead()
        flops = n * sparsity * d * k / num_machines
        bytes_scanned = n * d * sparsity / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        per_iter = (
            sparse_overhead * max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )
        if self.solver == "gram":
            iters_equiv = min(self._GRAM_FOLD_ITER_EQUIV, self.num_iterations)
            return iters_equiv * per_iter + mem_weight * d * d / num_machines
        return self.num_iterations * per_iter

    def resident_bytes(self, n, d, k, sparsity, num_machines) -> float:
        """Capacity model: padded-COO operand (int32 index + f32 value
        per stored cell — or the compressed tier's 4 B/nnz int16+bf16
        encoding when ``compress`` is set, infeasible past the int16
        index boundary), labels, history pairs; the gram engine adds
        its (d_pad)^2 f32 Gramian."""
        if self.compress is not None:
            from keystone_tpu.data import resident as resident_mod

            # +1: the append-ones intercept lane lives at index d.
            if not resident_mod.compressible_dim(d + 1):
                return float("inf")
            bytes_per_nnz = resident_mod.COMPRESSED_BYTES_PER_NNZ
        else:
            bytes_per_nnz = 8.0
        coo = bytes_per_nnz * n * d * sparsity / num_machines
        gram = 4.0 * d * d if self.solver == "gram" else 0.0
        return (
            coo
            + 4.0 * n * k / num_machines
            + 8.0 * _LBFGS_HISTORY * d * k
            + gram
        )
