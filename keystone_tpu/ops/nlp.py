"""NLP nodes: tokenization, n-grams, hashing TF, frequency encoding, n-gram
indexers, and the Stupid Backoff language model
(reference: nodes/nlp/{StringUtils,ngrams,HashingTF,NGramsHashingTF,
WordFrequencyEncoder,indexers,StupidBackoff}.scala).

Design stance: tokenization and n-gram bookkeeping are host-side work (they
are in the reference too — Scala collections inside RDD maps); the device
path begins once text becomes sparse/dense feature vectors. Hashes are
deterministic FNV-1a (Python's builtin ``hash`` is salted per process, which
would break cross-run reproducibility the reference gets from JVM ``.##``).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from keystone_tpu.data import Dataset
from keystone_tpu.workflow import Estimator, Transformer
from keystone_tpu.workflow.verify import HostSig, expect_host


# ---------------------------------------------------------------------------
# String transformers (reference: StringUtils.scala:13-29)
# ---------------------------------------------------------------------------
#
# These run host-side (jax.eval_shape cannot trace them), so each one
# DECLARES its static output signature for the plan verifier
# (workflow/verify.py): what host kind it consumes and what it emits.
# A text pipeline wired out of order (e.g. n-grams before tokenization)
# then fails verification with node coordinates instead of raising a
# confusing AttributeError mid-fit.


class Tokenizer(Transformer):
    """Split on a regex (default: runs of punctuation/whitespace)."""

    def __init__(self, sep: str = r"[^\w]+"):
        self.sep = re.compile(sep)

    def apply(self, s: str) -> List[str]:
        tokens = self.sep.split(s)
        # Java's String.split drops trailing empty strings but keeps leading
        # ones; match that (StringUtils.scala:14).
        while tokens and tokens[-1] == "":
            tokens.pop()
        return tokens

    def output_signature(self, sig):
        sig = expect_host(sig, ("str",), self)
        return HostSig("tokens", n=sig.n, datum=sig.datum)


class Trim(Transformer):
    def apply(self, s: str) -> str:
        return s.strip()

    def output_signature(self, sig):
        return expect_host(sig, ("str",), self)


class LowerCase(Transformer):
    def apply(self, s: str) -> str:
        return s.lower()

    def output_signature(self, sig):
        return expect_host(sig, ("str",), self)


# ---------------------------------------------------------------------------
# NGram value type + featurizer (reference: ngrams.scala:20-136)
# ---------------------------------------------------------------------------


class NGram:
    """Thin hashable wrapper over a tuple of words (ngrams.scala:100-131)."""

    __slots__ = ("words",)

    def __init__(self, words: Iterable):
        self.words = tuple(words)

    def __hash__(self) -> int:
        return hash(self.words)

    def __eq__(self, other) -> bool:
        return isinstance(other, NGram) and self.words == other.words

    def __repr__(self) -> str:
        return "[" + ",".join(str(w) for w in self.words) + "]"

    def __len__(self) -> int:
        return len(self.words)


class NGramsFeaturizer(Transformer):
    """Seq[T] -> all n-grams of the given consecutive orders, emitted in the
    reference's order: for each start position, ascending order length
    (ngrams.scala:20-97)."""

    def __init__(self, orders: Sequence[int]):
        self.orders = list(orders)
        self.min_order = min(self.orders)
        self.max_order = max(self.orders)
        if self.min_order < 1:
            raise ValueError(f"minimum order is not >= 1, found {self.min_order}")
        for a, b in zip(self.orders, self.orders[1:]):
            if b != a + 1:
                raise ValueError(f"orders are not consecutive; contains {a} and {b}")

    def apply(self, tokens: Sequence) -> List[Tuple]:
        out = []
        n = len(tokens)
        for i in range(n - self.min_order + 1):
            for order in range(self.min_order, self.max_order + 1):
                if i + order > n:
                    break
                out.append(tuple(tokens[i : i + order]))
        return out

    def output_signature(self, sig):
        sig = expect_host(sig, ("tokens", "int_tokens"), self)
        return HostSig("ngrams", n=sig.n, datum=sig.datum)


class NGramsCounts(Transformer):
    """Count n-gram occurrences over the whole dataset, returning a Dataset of
    (NGram, count) pairs sorted by descending count (ngrams.scala:152-185).

    mode="default" aggregates + sorts; mode="no_add" emits per-item counts
    without cross-item aggregation (NGramsCountsMode)."""

    def __init__(self, mode: str = "default"):
        if mode not in ("default", "no_add"):
            raise ValueError('mode must be "default" or "no_add"')
        self.mode = mode

    def apply(self, ngram_lists):
        counts = Counter(NGram(g) for g in ngram_lists)
        return list(counts.items())

    def batch_apply(self, data: Dataset) -> Dataset:
        if self.mode == "no_add":
            return Dataset.of([self.apply(item) for item in data.to_list()])
        counts: Counter = Counter()
        for item in data.to_list():
            counts.update(NGram(g) for g in item)
        ordered = sorted(counts.items(), key=lambda kv: -kv[1])
        return Dataset.of(ordered)

    def output_signature(self, sig):
        sig = expect_host(sig, ("ngrams", "tokens"), self)
        # The default mode aggregates ACROSS examples — the output count
        # is the number of distinct n-grams, not the input n.
        n = sig.n if self.mode == "no_add" else None
        return HostSig("ngram_counts", n=n, datum=sig.datum)


# ---------------------------------------------------------------------------
# Hashing TF (reference: HashingTF.scala:15-31, NGramsHashingTF.scala:25-120)
# ---------------------------------------------------------------------------

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def stable_hash(term) -> int:
    """Deterministic 64-bit FNV-1a over the term's string form (replaces the
    JVM's ``.##``, which is stable; Python's ``hash`` is salted)."""
    h = _FNV_OFFSET
    for b in str(term).encode("utf-8"):
        h ^= b
        h = (h * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def _ngram_hash(words: Tuple) -> int:
    """Stable hash of an n-gram that can be computed rolling: FNV-1a over the
    per-word hashes."""
    h = _FNV_OFFSET
    for w in words:
        wh = stable_hash(w)
        for _ in range(8):
            h ^= wh & 0xFF
            h = (h * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
            wh >>= 8
    return h


class HashingTF(Transformer):
    """Terms -> {index: frequency} via the hashing trick
    (HashingTF.scala:15-31). Single terms hash by value; tuple terms (n-grams)
    hash by the rolling n-gram hash so NGramsHashingTF matches exactly."""

    def __init__(self, num_features: int):
        self.num_features = num_features

    def term_index(self, term) -> int:
        h = _ngram_hash(term) if isinstance(term, tuple) else stable_hash(term)
        return h % self.num_features

    def apply(self, document: Sequence) -> Dict[int, float]:
        tf: Dict[int, float] = {}
        for term in document:
            i = self.term_index(term)
            tf[i] = tf.get(i, 0.0) + 1.0
        return tf

    def output_signature(self, sig):
        sig = expect_host(sig, ("tokens", "ngrams", "int_tokens"), self)
        return HostSig("tf_dict", n=sig.n, datum=sig.datum)


class NGramsHashingTF(Transformer):
    """Fused n-gram extraction + hashing TF, computing each n-gram's hash by
    extending the (order-1) prefix hash instead of materializing tuples —
    returns exactly HashingTF(NGramsFeaturizer(orders))
    (NGramsHashingTF.scala:25-120)."""

    def __init__(self, orders: Sequence[int], num_features: int):
        self._featurizer = NGramsFeaturizer(orders)  # validates orders
        self.orders = self._featurizer.orders
        self.num_features = num_features

    def apply(self, tokens: Sequence) -> Dict[int, float]:
        min_o, max_o = self._featurizer.min_order, self._featurizer.max_order
        n = len(tokens)
        word_hashes = [stable_hash(t) for t in tokens]
        tf: Dict[int, float] = {}
        for i in range(n - min_o + 1):
            h = _FNV_OFFSET
            for j in range(i, min(i + max_o, n)):
                wh = word_hashes[j]
                for _ in range(8):
                    h ^= wh & 0xFF
                    h = (h * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
                    wh >>= 8
                order = j - i + 1
                if order >= min_o:
                    idx = h % self.num_features
                    tf[idx] = tf.get(idx, 0.0) + 1.0
        return tf

    def output_signature(self, sig):
        sig = expect_host(sig, ("tokens", "int_tokens"), self)
        return HostSig("tf_dict", n=sig.n, datum=sig.datum)


# ---------------------------------------------------------------------------
# Word frequency encoding (reference: WordFrequencyEncoder.scala:7-62)
# ---------------------------------------------------------------------------


class WordFrequencyTransformer(Transformer):
    """Token -> frequency-rank index; out-of-vocabulary -> −1."""

    OOV_INDEX = -1

    def __init__(self, word_index: Dict[str, int], unigram_counts: Dict[int, int]):
        self.word_index = word_index
        self.unigram_counts = unigram_counts

    def apply(self, words: Sequence[str]) -> List[int]:
        return [self.word_index.get(w, self.OOV_INDEX) for w in words]

    def output_signature(self, sig):
        sig = expect_host(sig, ("tokens",), self)
        return HostSig("int_tokens", n=sig.n, datum=sig.datum)


class WordFrequencyEncoder(Estimator):
    """Fit the vocabulary sorted by descending frequency
    (WordFrequencyEncoder.scala:11-30)."""

    def fit(self, data: Dataset) -> WordFrequencyTransformer:
        counts: Counter = Counter()
        for tokens in data.to_list():
            counts.update(tokens)
        ordered = sorted(counts.items(), key=lambda kv: -kv[1])
        word_index = {w: i for i, (w, _) in enumerate(ordered)}
        unigram_counts = {word_index[w]: c for w, c in ordered}
        return WordFrequencyTransformer(word_index, unigram_counts)

    def fitted_signature(self, input_sigs):
        """Static signature of the fitted transformer's output at the
        delegating apply site (verifier contract)."""
        sig = input_sigs[0] if input_sigs else None
        if isinstance(sig, HostSig):
            return HostSig("int_tokens", n=sig.n, datum=sig.datum)
        return None


# ---------------------------------------------------------------------------
# Term frequency weighting lives in ops/stats.py (TermFrequency); lemmatizing
# n-grams (reference: CoreNLPFeatureExtractor.scala:18 — an external CoreNLP
# dependency) is provided as a pluggable-lemmatizer node.
# ---------------------------------------------------------------------------


def _default_lemmatizer(word: str) -> str:
    from keystone_tpu.ops.lemmatizer import lemmatize

    return lemmatize(word)


class CoreNLPFeatureExtractor(Transformer):
    """Sentence -> lemmatized n-grams. The reference shells out to Stanford
    CoreNLP (CoreNLPFeatureExtractor.scala:18); here the default lemmatizer
    is the in-tree Morpha-style inflectional analyzer
    (:mod:`keystone_tpu.ops.lemmatizer` — irregular-form table + detachment
    rule cascade, the same analysis class as CoreNLP's Morphology), and the
    lemmatizer stays a pluggable callable."""

    def __init__(self, orders: Sequence[int], lemmatizer: Optional[Callable[[str], str]] = None):
        self.featurizer = NGramsFeaturizer(orders)
        self.lemmatizer = lemmatizer or _default_lemmatizer
        self.tokenizer = Tokenizer()

    def apply(self, sentence: str) -> List[Tuple]:
        lemmas = [self.lemmatizer(t) for t in self.tokenizer.apply(sentence) if t]
        return self.featurizer.apply(lemmas)

    def output_signature(self, sig):
        sig = expect_host(sig, ("str",), self)
        return HostSig("ngrams", n=sig.n, datum=sig.datum)


# ---------------------------------------------------------------------------
# N-gram indexers (reference: indexers.scala:5-135)
# ---------------------------------------------------------------------------


class NGramIndexer:
    min_ngram_order = 1
    max_ngram_order = 5

    def pack(self, ngram: Sequence) -> Any:
        raise NotImplementedError


class BackoffIndexer(NGramIndexer):
    def unpack(self, ngram, pos: int):
        raise NotImplementedError

    def remove_farthest_word(self, ngram):
        raise NotImplementedError

    def remove_current_word(self, ngram):
        raise NotImplementedError

    def ngram_order(self, ngram) -> int:
        raise NotImplementedError


class NGramIndexerImpl(BackoffIndexer):
    """NGram-tuple indexer (indexers.scala:117-135)."""

    def pack(self, ngram: Sequence) -> NGram:
        return NGram(ngram)

    def unpack(self, ngram: NGram, pos: int):
        return ngram.words[pos]

    def remove_farthest_word(self, ngram: NGram) -> NGram:
        return NGram(ngram.words[1:])

    def remove_current_word(self, ngram: NGram) -> NGram:
        return NGram(ngram.words[:-1])

    def ngram_order(self, ngram: NGram) -> int:
        return len(ngram.words)


class NaiveBitPackIndexer(BackoffIndexer):
    """Packs up to 3 word ids (< 2^20) into one 64-bit int, 4 control bits +
    three 20-bit fields, left-aligned (indexers.scala:43-115)."""

    min_ngram_order = 1
    max_ngram_order = 3
    _MASK20 = (1 << 20) - 1

    def pack(self, ngram: Sequence[int]) -> int:
        for w in ngram:
            if w >= 1 << 20:
                raise ValueError(f"word id {w} >= 2^20")
        n = len(ngram)
        if n == 1:
            return ngram[0] << 40
        if n == 2:
            return (ngram[1] << 20) | (ngram[0] << 40) | (1 << 60)
        if n == 3:
            return ngram[2] | (ngram[1] << 20) | (ngram[0] << 40) | (1 << 61)
        raise ValueError("ngram order must be in {1, 2, 3}")

    def unpack(self, ngram: int, pos: int) -> int:
        if pos == 0:
            return (ngram >> 40) & self._MASK20
        if pos == 1:
            return (ngram >> 20) & self._MASK20
        if pos == 2:
            return ngram & self._MASK20
        raise ValueError("pos must be in {0, 1, 2}")

    def ngram_order(self, ngram: int) -> int:
        order = (ngram >> 60) & 0xF
        if not (self.min_ngram_order <= order + 1 <= self.max_ngram_order):
            raise ValueError(f"raw control bits {order} are invalid")
        return order + 1

    def remove_farthest_word(self, ngram: int) -> int:
        order = self.ngram_order(ngram)
        stripped = ngram & ((1 << 40) - 1)
        shifted = stripped << 20
        if order == 2:
            return shifted & ~(0xF << 60)
        if order == 3:
            return (shifted & ~(0xF << 60)) | (1 << 60)
        raise ValueError(f"ngram order not supported: {order}")

    def remove_current_word(self, ngram: int) -> int:
        order = self.ngram_order(ngram)
        if order == 2:
            return (ngram & ~((1 << 40) - 1)) & ~(0xF << 60)
        if order == 3:
            return ((ngram & ~((1 << 20) - 1)) & ~(0xF << 60)) | (1 << 60)
        raise ValueError(f"ngram order not supported: {order}")


# ---------------------------------------------------------------------------
# Stupid Backoff LM (reference: StupidBackoff.scala:25-182; Brants et al. 2007)
# ---------------------------------------------------------------------------


def initial_bigram_partition(ngram, num_partitions: int, indexer: BackoffIndexer) -> int:
    """Partition id by hashing the first two context words — groups n-grams
    sharing their initial bigram (InitialBigramPartitioner,
    StupidBackoff.scala:25-58). On TPU this is the host-side shard key for
    multi-host score tables rather than a Spark shuffle partitioner."""
    if indexer.ngram_order(ngram) > 1:
        first = indexer.unpack(ngram, 0)
        second = indexer.unpack(ngram, 1)
        return _ngram_hash((first, second)) % num_partitions
    return 0


def _score_locally(
    indexer: BackoffIndexer,
    unigram_counts: Dict[Any, int],
    get_ngram_count: Callable,
    num_tokens: int,
    alpha: float,
    accum: float,
    ngram,
    ngram_freq: int,
) -> float:
    """Recursive backoff score S(w | context) (StupidBackoff.scala:62-93)."""
    while True:
        order = indexer.ngram_order(ngram)
        if order == 1:
            return accum * ngram_freq / num_tokens
        if ngram_freq != 0:
            context = indexer.remove_current_word(ngram)
            if order != 2:
                context_freq = get_ngram_count(context)
            else:
                context_freq = unigram_counts.get(indexer.unpack(context, 0), 0)
            return accum * ngram_freq / context_freq
        backoffed = indexer.remove_farthest_word(ngram)
        if order != 2:
            freq = get_ngram_count(backoffed)
        else:
            freq = unigram_counts.get(indexer.unpack(backoffed, 0), 0)
        accum *= alpha
        ngram = backoffed
        ngram_freq = freq


class _PackedCountTable:
    """Sorted packed-int64 → count table for vectorized lookups.

    The dict-of-NGram serving path answers one Python call per query; batch
    serving instead packs the whole table once (NaiveBitPackIndexer wire
    format — the same packing :func:`pack_ngram_pairs` ships across hosts)
    and answers a query ARRAY with one ``searchsorted`` per backoff level.
    """

    def __init__(self, packed_keys, counts):
        import numpy as np

        order = np.argsort(packed_keys, kind="stable")
        self.keys = np.asarray(packed_keys, dtype=np.int64)[order]
        self.counts = np.asarray(counts, dtype=np.int64)[order]

    @classmethod
    def from_ngram_counts(cls, ngram_counts: Dict[NGram, int]):
        import numpy as np

        packer = NaiveBitPackIndexer()
        keys = np.empty(len(ngram_counts), dtype=np.int64)
        cnts = np.empty(len(ngram_counts), dtype=np.int64)
        for i, (g, c) in enumerate(ngram_counts.items()):
            words = g.words if isinstance(g, NGram) else tuple(g)
            keys[i] = packer.pack(words)
            cnts[i] = int(c)
        return cls(keys, cnts)

    @classmethod
    def from_unigram_counts(cls, unigram_counts: Dict[Any, int]):
        """Unigrams keyed by bare word id, stored in packed-unigram form
        (id << 40) so lookups share one code path."""
        import numpy as np

        keys = np.fromiter(
            (int(w) << 40 for w in unigram_counts), dtype=np.int64,
            count=len(unigram_counts),
        )
        cnts = np.fromiter(
            (int(c) for c in unigram_counts.values()), dtype=np.int64,
            count=len(unigram_counts),
        )
        return cls(keys, cnts)

    def lookup(self, packed):
        """Counts for a packed int64 query array (0 where absent)."""
        import numpy as np

        pos = np.searchsorted(self.keys, packed)
        pos = np.minimum(pos, len(self.keys) - 1) if len(self.keys) else pos
        if not len(self.keys):
            return np.zeros(packed.shape, dtype=np.int64)
        hit = self.keys[pos] == packed
        return np.where(hit, self.counts[pos], 0)


# Vectorized NaiveBitPackIndexer field ops (mirror indexers.scala:43-115).
# Control bits live at 60-61, so "clear control bits" is a keep-low-60 mask
# — ~(0xF << 60) does not fit a signed int64 and would overflow numpy.
_M20 = (1 << 20) - 1
_M40 = (1 << 40) - 1
_KEEP60 = (1 << 60) - 1


def _vec_order(packed):
    return ((packed >> 60) & 0xF) + 1


def _vec_first_word(packed):
    return (packed >> 40) & _M20


def _vec_remove_current(packed):
    """Drop the last word (the context of the prediction)."""
    import numpy as np

    order = _vec_order(packed)
    two = (packed & ~_M40) & _KEEP60
    three = ((packed & ~np.int64(_M20)) & _KEEP60) | (1 << 60)
    return np.where(order == 2, two, three)


def _vec_remove_farthest(packed):
    """Drop the first word (the backoff step)."""
    import numpy as np

    order = _vec_order(packed)
    shifted = ((packed & _M40) << 20) & _KEEP60
    two = shifted
    three = shifted | (1 << 60)
    return np.where(order == 2, two, three)


def _batch_score_packed(
    packed,
    count_fn,
    unigram_table: "_PackedCountTable",
    num_tokens: int,
    alpha: float,
):
    """Vectorized backoff scoring: every element of the packed query array
    advances one backoff level per pass (max 3 levels for orders ≤ 3), with
    each level's count lookups batched through ``count_fn`` (one
    searchsorted over the sorted table instead of one dict probe per
    query). Same recursion as :func:`_score_locally`
    (StupidBackoff.scala:62-93) — the dict loop remains the oracle."""
    import numpy as np

    packed = np.asarray(packed, dtype=np.int64)
    # Process queries in sorted order: searchsorted over a large table is
    # ~10x faster on sorted queries (branch path locality), which beats
    # the one-time argsort well before typical serving batch sizes.
    unsort = None
    if packed.size > 4096:
        order = np.argsort(packed, kind="stable")
        unsort = np.empty_like(order)
        unsort[order] = np.arange(order.size)
        packed = packed[order]

    cur = np.array(packed, dtype=np.int64, copy=True)
    accum = np.ones(cur.shape, dtype=np.float64)
    out = np.zeros(cur.shape, dtype=np.float64)
    active = np.ones(cur.shape, dtype=bool)
    # The carried frequency mirrors the oracle's ``ngram_freq`` argument:
    # the TOP-level lookup always reads the n-gram table (a top-level
    # unigram query therefore scores 0 when the fit held only orders > 1 —
    # exactly the dict loop's behavior); after a backoff from order 2 the
    # frequency comes from the unigram table instead.
    freq = count_fn(cur)

    # Orders are ≤ 3, so at most 3 passes; guard with the loop bound anyway.
    for _ in range(4):
        if not active.any():
            break
        order = _vec_order(cur)

        # Terminal: score = accum * carried_freq / num_tokens.
        uni = active & (order == 1)
        if uni.any():
            out[uni] = accum[uni] * freq[uni] / num_tokens
            active = active & ~uni

        if not active.any():
            break
        idx = np.nonzero(active)[0]

        # Observed: score = accum * c(ngram) / c(context). Each subset hits
        # only its own table (an np.where over both lookups would evaluate
        # both for every element — S wasted searchsorted passes per level
        # on a sharded count_fn).
        hit = freq[idx] != 0
        if hit.any():
            hidx = idx[hit]
            ctx = _vec_remove_current(cur[hidx])
            o2 = _vec_order(cur[hidx]) == 2
            ctx_freq = np.empty(len(hidx), dtype=np.int64)
            if o2.any():
                # An order-2 context IS a packed unigram.
                ctx_freq[o2] = unigram_table.lookup(ctx[o2])
            if (~o2).any():
                ctx_freq[~o2] = count_fn(ctx[~o2])
            if (ctx_freq == 0).any():
                # Count tables violating the context-consistency invariant
                # (an observed n-gram whose context was never counted)
                # crash the dict oracle with ZeroDivisionError; silently
                # emitting inf here would let bad scores flow into ranking.
                raise ZeroDivisionError(
                    "observed n-gram with zero context count — the count "
                    "table violates the context-consistency invariant"
                )
            out[hidx] = accum[hidx] * freq[hidx] / ctx_freq
            active[hidx] = False

        # Unobserved: back off (drop the farthest word, discount by α).
        midx = idx[~hit]
        if len(midx):
            backoffed = _vec_remove_farthest(cur[midx])
            o2 = _vec_order(cur[midx]) == 2
            new_freq = np.empty(len(midx), dtype=np.int64)
            if o2.any():
                new_freq[o2] = unigram_table.lookup(backoffed[o2])
            if (~o2).any():
                new_freq[~o2] = count_fn(backoffed[~o2])
            freq[midx] = new_freq
            cur[midx] = backoffed
            accum[midx] *= alpha
    return out if unsort is None else out[unsort]


class StupidBackoffModel(Transformer):
    """Query-only LM model: use ``score(ngram)`` for single queries or
    ``batch_score`` / ``batch_score_packed`` for vectorized serving
    (StupidBackoff.scala:96-125)."""

    def __init__(
        self,
        scores: Dict[NGram, float],
        ngram_counts: Dict[NGram, int],
        indexer: BackoffIndexer,
        unigram_counts: Dict[Any, int],
        num_tokens: int,
        alpha: float = 0.4,
    ):
        self.scores = scores
        self.ngram_counts = ngram_counts
        self.indexer = indexer
        self.unigram_counts = unigram_counts
        self.num_tokens = num_tokens
        self.alpha = alpha
        self._table = None
        self._uni_table = None

    def score(self, ngram: NGram) -> float:
        return _score_locally(
            self.indexer,
            self.unigram_counts,
            lambda g: self.ngram_counts.get(g, 0),
            self.num_tokens,
            self.alpha,
            1.0,
            ngram,
            self.ngram_counts.get(ngram, 0),
        )

    def _tables(self):
        if self._table is None:
            self._table = _PackedCountTable.from_ngram_counts(self.ngram_counts)
            self._uni_table = _PackedCountTable.from_unigram_counts(
                self.unigram_counts
            )
        return self._table, self._uni_table

    def batch_score_packed(self, packed):
        """Vectorized scores for a packed int64 n-gram array (the
        :func:`pack_ngram_pairs` wire format; integer word ids < 2^20,
        orders 1-3). The reference served scoring data-parallel over the
        cluster (StupidBackoff.scala:128-182); this is the one-host
        vectorized analog — same recursion, table lookups batched."""
        table, uni = self._tables()
        return _batch_score_packed(
            packed, table.lookup, uni, self.num_tokens, self.alpha
        )

    def batch_score(self, ngrams: Sequence) -> "Any":
        """Pack + vectorized-score a sequence of NGram / word-id tuples."""
        import numpy as np

        packer = NaiveBitPackIndexer()
        packed = np.fromiter(
            (
                packer.pack(g.words if isinstance(g, NGram) else tuple(g))
                for g in ngrams
            ),
            dtype=np.int64,
            count=len(ngrams),
        )
        return self.batch_score_packed(packed)

    def apply(self, ignored):
        raise NotImplementedError(
            "Doesn't make sense to chain this node; use score(ngram) to query."
        )


def partition_ngram_pairs(
    pairs, num_partitions: int, indexer: Optional[BackoffIndexer] = None
):
    """reduceByKey with the InitialBigramPartitioner, host side
    (StupidBackoff.scala:152-156): merge duplicate n-gram counts and bucket
    them by :func:`initial_bigram_partition`. Returns a list of
    ``num_partitions`` lists of (NGram, count).

    The partitioner's invariant makes per-partition scoring exact: an
    n-gram's context (its first n−1 words) shares the initial bigram, so
    every count the score recursion reads for an OBSERVED n-gram lives in
    the same partition (order-2 contexts read the replicated unigram table
    instead), and the freq==0 backoff branch is unreachable during fit.
    """
    indexer = indexer or NGramIndexerImpl()
    merged: Dict[NGram, int] = {}
    for ngram, c in pairs:
        key = ngram if isinstance(ngram, NGram) else NGram(ngram)
        merged[key] = merged.get(key, 0) + int(c)
    parts = [[] for _ in range(num_partitions)]
    for ngram, c in merged.items():
        parts[initial_bigram_partition(ngram, num_partitions, indexer)].append(
            (ngram, c)
        )
    return parts


def pack_ngram_pairs(pairs) -> "np.ndarray":
    """(NGram, count) pairs -> (m, 2) int64 array ``[packed_id, count]`` —
    the wire format for exchanging count shards across hosts as ONE device
    array (all_gather over DCN) instead of pickled host objects. Uses
    NaiveBitPackIndexer: integer word ids < 2^20, orders 1-3
    (indexers.scala:43-115).

    The packed ids use up to 62 bits: callers moving this array through
    device collectives must run with jax x64 enabled, or the values are
    silently truncated to int32."""
    import numpy as np

    packer = NaiveBitPackIndexer()
    out = np.empty((len(pairs), 2), dtype=np.int64)
    for i, (ngram, c) in enumerate(pairs):
        words = ngram.words if isinstance(ngram, NGram) else tuple(ngram)
        out[i, 0] = packer.pack(words)
        out[i, 1] = int(c)
    return out


def unpack_ngram_pairs(arr) -> List[Tuple[NGram, int]]:
    """Inverse of :func:`pack_ngram_pairs`."""
    packer = NaiveBitPackIndexer()
    out = []
    for packed, c in arr.tolist():
        order = packer.ngram_order(packed)
        words = tuple(packer.unpack(packed, p) for p in range(order))
        out.append((NGram(words), int(c)))
    return out


class ShardedStupidBackoffModel(Transformer):
    """Multi-host LM serving: one StupidBackoffModel per initial-bigram
    partition. EVERY count lookup routes to its owning shard — not just the
    top-level query — because the backoff step drops the FIRST word, which
    changes the initial bigram and so the owning partition. This mirrors
    the reference's ``ngramCounts.lookup`` on the partitioned RDD, where
    the partitioner routes each lookup (StupidBackoff.scala:96-125)."""

    # Keys probed per shard by the default disjointness check.
    _VALIDATE_PROBES = 32

    def __init__(self, shards: List["StupidBackoffModel"], indexer=None,
                 validate=True):
        self.shards = shards
        self.indexer = indexer or NGramIndexerImpl()
        # batch_score_packed SUMS per-shard lookups, which is only equal to
        # the routed lookup when no n-gram lives in two shards — guaranteed
        # by partition_ngram_pairs but not by a hand-assembled model, where
        # a duplicate would silently double its count.
        #
        # The DEFAULT check is a sampled-key probe: O(shards² × probes)
        # dict lookups instead of materializing a set union of every
        # shard's n-grams (O(total n-grams) time AND memory — at serving
        # scale that doubled construction's footprint for a check that, in
        # the realistic failure mode of the same pair list fed to two
        # shards, any single probed key already catches). Probabilistic:
        # it cannot prove disjointness. Pass ``validate="full"`` for the
        # exhaustive union check, or ``validate=False`` to skip — the
        # partitioner's own construction path (:meth:`from_partitioned`)
        # does, since its shards are disjoint by construction.
        if validate == "full":
            total = sum(len(s.ngram_counts) for s in shards)
            union: set = set()
            for s in shards:
                union.update(s.ngram_counts)
            if len(union) != total:
                raise ValueError(
                    f"shards overlap: {total - len(union)} n-gram(s) present "
                    "in more than one shard (partition with "
                    "partition_ngram_pairs)"
                )
        elif validate:
            self._probe_disjoint()

    def _probe_disjoint(self) -> None:
        """Sampled disjointness check: probe evenly-spaced keys from each
        shard against every other shard's table. Probabilistic — it cannot
        prove disjointness, but catches the systematic overlaps
        mis-assembly actually produces (duplicated or mis-partitioned pair
        lists) at O(probes) memory (the keys are stepped off the dict
        iterator, never materialized as a full list)."""
        from itertools import islice

        for i, s in enumerate(self.shards):
            count = len(s.ngram_counts)
            if not count:
                continue
            step = max(count // self._VALIDATE_PROBES, 1)
            probes = list(islice(
                iter(s.ngram_counts), 0, step * self._VALIDATE_PROBES, step
            ))
            for j, other in enumerate(self.shards):
                if j == i:
                    continue
                for key in probes:
                    if key in other.ngram_counts:
                        raise ValueError(
                            f"shards overlap: n-gram {key} present in "
                            f"shards {i} and {j} (partition with "
                            "partition_ngram_pairs; probabilistic probe — "
                            'pass validate="full" for the exhaustive check)'
                        )

    @classmethod
    def from_partitioned(
        cls, shards: List["StupidBackoffModel"], indexer=None
    ) -> "ShardedStupidBackoffModel":
        """Construction path for shards fitted from
        :func:`partition_ngram_pairs` output: the partitioner assigns each
        n-gram to exactly one part, so the overlap check is skipped
        entirely (validate=False) — no O(total n-grams) pass at serving
        scale."""
        return cls(shards, indexer=indexer, validate=False)

    def _count(self, ngram: NGram) -> int:
        pid = initial_bigram_partition(ngram, len(self.shards), self.indexer)
        return self.shards[pid].ngram_counts.get(ngram, 0)

    def score(self, ngram: NGram) -> float:
        head = self.shards[0]  # unigram table/α replicated across shards
        return _score_locally(
            self.indexer,
            head.unigram_counts,
            self._count,
            head.num_tokens,
            head.alpha,
            1.0,
            ngram,
            self._count(ngram),
        )

    def batch_score_packed(self, packed):
        """Vectorized scoring against the sharded tables. Every n-gram lives
        in exactly ONE shard (the partitioner is a function of the key), so
        summing per-shard lookups equals the routed lookup — no per-query
        partition hashing, one searchsorted per shard per backoff level."""
        head = self.shards[0]
        tables = [s._tables()[0] for s in self.shards]
        uni = head._tables()[1]

        def count_fn(arr):
            total = tables[0].lookup(arr)
            for t in tables[1:]:
                total = total + t.lookup(arr)
            return total

        return _batch_score_packed(
            packed, count_fn, uni, head.num_tokens, head.alpha
        )

    def apply(self, ignored):
        raise NotImplementedError(
            "Doesn't make sense to chain this node; use score(ngram) to query."
        )


class StupidBackoffEstimator(Estimator):
    """Scores every observed n-gram (StupidBackoff.scala:128-182). Input: a
    Dataset of (NGram, count) pairs, e.g. from NGramsCounts."""

    def __init__(self, unigram_counts: Dict[Any, int], alpha: float = 0.4):
        self.unigram_counts = unigram_counts
        self.alpha = alpha
        self.indexer = NGramIndexerImpl()

    def fit(self, data: Dataset) -> StupidBackoffModel:
        counts: Dict[NGram, int] = {}
        for ngram, c in data.to_list():
            key = ngram if isinstance(ngram, NGram) else NGram(ngram)
            counts[key] = counts.get(key, 0) + int(c)
        num_tokens = sum(self.unigram_counts.values())

        get_count = lambda g: counts.get(g, 0)
        scores: Dict[NGram, float] = {}
        for ngram, freq in counts.items():
            s = _score_locally(
                self.indexer,
                self.unigram_counts,
                get_count,
                num_tokens,
                self.alpha,
                1.0,
                ngram,
                freq,
            )
            if not (0.0 <= s <= 1.0):
                raise ValueError(f"score = {s:.4f} not in [0,1], ngram = {ngram}")
            scores[ngram] = s
        return StupidBackoffModel(
            scores, counts, self.indexer, self.unigram_counts, num_tokens, self.alpha
        )
