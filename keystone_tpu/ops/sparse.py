"""Sparse feature nodes (reference: nodes/util/CommonSparseFeatures.scala:20-64,
AllSparseFeatures.scala:15-27, SparseFeatureVectorizer.scala:7-17,
Densify.scala:10-21, Sparsify.scala:10-20).

TPU-native sparse batch format: padded COO per row —
``{"indices": (n, max_nnz) int32 (−1 padding), "values": (n, max_nnz)}``
carried as a Dataset pytree.

The sparse compute tier never densifies: ``sparse_matmul`` (X @ W) is a
gather over the model rows + a reduction over the nnz axis, and
``sparse_matmul_t`` (Xᵀ V) is a segment-sum scatter over the flattened
active indices — the TPU formulation of the reference's hand-rolled
active-index gradient loops (Gradient.scala:58-123). At Amazon-review scale
(n=65e6, d=16384, sparsity≈0.005 — scripts/constantEstimator.R:34) the
padded-COO operands are ~100× smaller than the dense design matrix the old
densify path would have materialized. ``densify_dataset`` remains for small
inputs where one dense GEMM beats gather+scatter dispatch.

Measured characteristics (v5e): both kernels run at the chip's
random-access rate — 129M indices/s on the raw column-take microbenchmark,
179M/s inside the full LBFGS solve (bench.py's amazon row, round 3; earlier
rounds' 65M/s figure predates the per-column layouts) — which is the honest
TPU trade-off for this workload class: the sparse tier is a *capacity* play
(dense f32 would be 131 GB at n=2e6 and ~4.3 TB at the full n=65e6; the
COO itself is ~43 GB at n=65e6 — int16+bf16 compression and the streamed
gram tier below are what actually cross that wall), not a FLOP play. A
transposed-layout gather variant and a complex-packed gather were measured
and do not beat the scatter, so the simple formulations stay. Layout rule
learned the hard way: never put a tiny label dimension minor-most in a big
intermediate — TPU tiling lane-pads it to 128 (an 85 GB transient at this
scale), hence the per-column small-k formulations below.
"""

from __future__ import annotations

import functools
import heapq
from collections import Counter
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.workflow import Estimator, Transformer


def _item_pairs(item) -> List[Tuple[Any, float]]:
    """Normalize a sparse item: dict or iterable of (feature, value)."""
    if isinstance(item, dict):
        return list(item.items())
    return list(item)


def sparse_batch_from_items(
    items: Sequence, feature_index: Dict[Any, int], max_nnz: Optional[int] = None
) -> Dataset:
    """Host items (feature, value) -> padded-COO device batch over a vocab."""
    rows = []
    for item in items:
        pairs = [
            (feature_index[f], v) for f, v in _item_pairs(item) if f in feature_index
        ]
        pairs.sort()
        rows.append(pairs)
    width = max_nnz or max((len(r) for r in rows), default=1)
    width = max(width, 1)
    n = len(rows)
    indices = np.full((n, width), -1, dtype=np.int32)
    values = np.zeros((n, width), dtype=np.float32)
    for i, pairs in enumerate(rows):
        pairs = pairs[:width]
        if pairs:
            idx, val = zip(*pairs)
            indices[i, : len(idx)] = idx
            values[i, : len(val)] = val
    return Dataset({"indices": indices, "values": values}, n=n)


def is_sparse_dataset(data: Dataset) -> bool:
    return (
        not data.is_host
        and isinstance(data.data, dict)
        and set(data.data.keys()) == {"indices", "values"}
    )


def densify_dataset(data: Dataset, num_features: Optional[int] = None) -> Dataset:
    """Padded-COO batch -> dense (n, d) batch (one scatter-add per batch)."""
    if not is_sparse_dataset(data):
        return data
    indices = jnp.asarray(data.data["indices"])
    values = jnp.asarray(data.data["values"])
    d = num_features if num_features is not None else int(indices.max()) + 1
    return Dataset(_scatter_dense(indices, values, d), n=data.n, mesh=data.mesh)


# Label widths up to this take the per-column formulation, whose
# intermediates are all rank-1/2 with the LARGE axis minor — a (n·max_nnz, k)
# layout with tiny k would be lane-padded to 128 by the TPU tiling (a 64x
# HBM blowup at Amazon scale: 85 GB for n=2e6, k=2).
_COLWISE_MAX_K = 32
_CHUNK_ELEMS = 1 << 20  # row-chunk size divisor for the wide-k paths


def _row_chunks(safe, vals, k, pad_index=0):
    """Split (n, w) index/value arrays into (nchunks, chunk, w) row chunks
    for the wide-k paths, bounding each chunk's (chunk, w, k) transient at
    ~_CHUNK_ELEMS elements. The chunk is capped at n so small batches are
    not inflated to the chunk quantum."""
    n, w = safe.shape
    chunk = min(max(n, 1), max(1, _CHUNK_ELEMS // max(w * k, 1)))
    nchunks = -(-n // chunk)
    pad = nchunks * chunk - n
    safe_p = jnp.pad(safe, ((0, pad), (0, 0)), constant_values=pad_index)
    vals_p = jnp.pad(vals, ((0, pad), (0, 0)))
    return (
        safe_p.reshape(nchunks, chunk, w),
        vals_p.reshape(nchunks, chunk, w),
        nchunks,
        chunk,
        pad,
    )


@jax.jit
def sparse_matmul(indices, values, W):
    """X @ W for a padded-COO X without densifying.

    out[i] = Σ_j values[i, j] · W[indices[i, j], :] — a gather of the model
    rows at the active indices plus a reduction over the nnz axis (the
    active-index inner loops of LeastSquaresSparseGradient,
    Gradient.scala:58-123, become one vectorized gather+sum). Cost is
    O(n · max_nnz · k) independent of d. Indices outside [0, d) are dropped
    (the same semantics as the densify scatter and sparse_matmul_t — the
    X and Xᵀ operators must agree or gradients silently corrupt).

    Small k gathers one model column at a time so every intermediate is
    (n, max_nnz) — no lane-padding blowup; wide k runs the (chunk, w, k)
    gather over row chunks via lax.map to bound the transient.
    """
    k = W.shape[1]
    mask = (indices >= 0) & (indices < W.shape[0])
    safe = jnp.where(mask, indices, 0)
    vals = jnp.where(mask, values, 0.0).astype(W.dtype)
    if k <= _COLWISE_MAX_K:
        cols = [
            jnp.sum(vals * jnp.take(W[:, c], safe), axis=1) for c in range(k)
        ]
        return jnp.stack(cols, axis=1)

    safe_p, vals_p, nchunks, chunk, _ = _row_chunks(safe, vals, k)

    def body(xs):
        s, va = xs
        return jnp.einsum("cw,cwk->ck", va, jnp.take(W, s, axis=0))

    out = jax.lax.map(body, (safe_p, vals_p)).reshape(nchunks * chunk, k)
    return out[: indices.shape[0]]


@functools.partial(jax.jit, static_argnames=("d",))
def sparse_matmul_t(indices, values, V, d: int):
    """Xᵀ @ V for a padded-COO X via segment-sum scatters.

    Every active (i, j) contributes ``values[i, j] · V[i, :]`` to output row
    ``indices[i, j]``; padding and out-of-range lanes scatter into a ghost
    bucket that is sliced off (dropped — matching sparse_matmul). This is
    the transpose pass of the sparse gradient — together
    with :func:`sparse_matmul` it gives the full Xᵀ(XW − Y) gradient without
    ever materializing a dense design matrix.

    Small k scatters one output column at a time (each a flat (n·max_nnz,)
    segment sum — no lane-padded (n·max_nnz, k) tensor); wide k accumulates
    row-chunked scatters in a scan.
    """
    n, w = indices.shape
    k = V.shape[1]
    mask = (indices >= 0) & (indices < d)
    safe = jnp.where(mask, indices, d)  # ghost bucket d for padding
    vals = jnp.where(mask, values, 0.0).astype(V.dtype)
    if k <= _COLWISE_MAX_K:
        flat_ids = safe.reshape(-1)
        cols = [
            jax.ops.segment_sum(
                (vals * V[:, c][:, None]).reshape(n * w),
                flat_ids,
                num_segments=d + 1,
            )
            for c in range(k)
        ]
        return jnp.stack(cols, axis=1)[:d]

    safe_p, vals_p, nchunks, chunk, pad = _row_chunks(
        safe, vals, k, pad_index=d
    )
    V_p = jnp.pad(V, ((0, pad), (0, 0))).reshape(nchunks, chunk, k)

    def body(acc, xs):
        s, va, vv = xs
        contrib = (va[:, :, None] * vv[:, None, :]).reshape(chunk * w, k)
        return acc + jax.ops.segment_sum(
            contrib, s.reshape(-1), num_segments=d + 1
        ), None

    out, _ = jax.lax.scan(
        body,
        jnp.zeros((d + 1, k), dtype=V.dtype),
        (safe_p, vals_p, V_p),
    )
    return out[:d]


def gram_pad_dim(d: int, val_dtype) -> int:
    """Column padding for :func:`sparse_gram_stream`'s dense slabs: round d
    up to the accumulating-syrk column tile (zero columns contribute zero
    Gramian rows/cols, and zero-initialized solver blocks stay exactly
    zero, so callers may solve on the padded shape and slice)."""
    tile = 1024 if jnp.dtype(val_dtype) == jnp.bfloat16 else 512
    return -(-d // tile) * tile


def sparse_gram_stream(
    chunk_fn,
    num_chunks: int,
    d: int,
    k: int,
    use_pallas: bool = False,
    val_dtype=jnp.float32,
    pipeline: bool = True,
):
    """Fold (G = AᵀA, AᵀY, ΣY²) over padded-COO row chunks — the sparse
    arm of the out-of-core streaming tier (parallel/streaming.py).

    ``chunk_fn(cid)`` returns ``(indices (c, w) int, values (c, w), Y
    (c, k))`` for chunk ``cid`` — sliced from resident (possibly
    int16/bf16-compressed) buffers, or REGENERATED/loaded per chunk so the
    full dataset never exists on device. Negative indices are inactive
    lanes.

    Each chunk is DENSIFIED into a (c, d_pad) slab and folded through the
    accumulating symmetric Pallas kernel. Deliberately so: at TPU rates —
    dense bf16 GEMM ~150 TF/s vs ~2e8 random accesses/s — the ~200
    "wasted" multiplies per zero at Amazon sparsity (0.005) still beat
    per-element gather/scatter by an order of magnitude for AᵀA, and the
    L-BFGS iterations on the folded G then cost no data pass at all
    (ops/learning/lbfgs.py::_lbfgs_gram_core). This is the same
    per-partition Gramian + treeReduce pattern as the dense tier
    (BlockWeightedLeastSquares.scala:177-313), with densify-then-syrk as
    the per-partition kernel.

    Returns (G, AtY, yty) at d_pad = :func:`gram_pad_dim` (slice [:d] to
    drop the padding). Traceable — call under jit. For dispatch-bounded
    SEGMENTED folding (long chunk streams must not run as one multi-minute
    program on hosts with dispatch watchdogs), use :func:`sparse_gram_fold`
    over cid ranges and :func:`gram_finalize` once at the end.
    ``pipeline`` is the double-buffer knob of :func:`sparse_gram_fold` —
    pass False when an extra resident chunk slab would bust HBM (e.g. the
    bench's resident-capacity probe beside a 9.8 GB COO).
    """
    carry = sparse_gram_fold(
        None, jnp.arange(num_chunks), chunk_fn, d, k,
        use_pallas=use_pallas, val_dtype=val_dtype, pipeline=pipeline,
    )
    G, AtY, yty = carry
    return gram_finalize(G), AtY, yty


def sparse_gram_init(d: int, k: int, val_dtype=jnp.float32):
    """Zero (G_raw, AtY, yty) carry for :func:`sparse_gram_fold`."""
    d_pad = gram_pad_dim(d, val_dtype)
    return (
        jnp.zeros((d_pad, d_pad), jnp.float32),
        jnp.zeros((d_pad, k), jnp.float32),
        jnp.zeros((), jnp.float32),
    )


def gram_finalize(G):
    """Mirror the accumulated upper triangle into a full symmetric G."""
    return jnp.triu(G) + jnp.triu(G, 1).T


def sparse_gram_fold(
    carry,
    cids,
    chunk_fn,
    d: int,
    k: int,
    use_pallas: bool = False,
    val_dtype=jnp.float32,
    pipeline: bool = True,
):
    """Fold the chunk ids ``cids`` into the (G_raw, AtY, yty) carry.

    ``carry=None`` starts fresh (:func:`sparse_gram_init`). G_raw carries
    the accumulating-syrk upper-triangle contract — call
    :func:`gram_finalize` after the LAST fold. Traceable.

    Two chunk-loop structures (identical results — same chunk order, same
    per-chunk arithmetic):

    - ``pipeline=True`` (default): the scan carry holds the NEXT chunk's
      densified slab, so each step folds slab k while regenerating +
      scattering slab k+1 — the two are data-independent inside one step,
      which hands the scheduler regen/densify work (VPU + scatter) to
      overlap with the accumulating syrk (MXU), the device-compute analog
      of ``data/prefetch.py``'s host-side double buffer. Costs one extra
      resident chunk slab (c × d_pad of ``val_dtype``).
    - ``pipeline=False``: the round-5 serial body (regen → densify →
      fold per step); one slab resident. Use when the extra slab busts
      HBM (resident-capacity probes).

    When ``use_pallas`` and the slab is tile-aligned
    (:func:`~keystone_tpu.ops.pallas_ops.gram_corr_acc_ok`), the chunk
    step is ONE accumulating Pallas kernel — syrk + correlation fused
    (:func:`~keystone_tpu.ops.pallas_ops.gram_corr_sym_acc`), so the
    separate AᵀY GEMM's full re-read of the slab from HBM disappears.
    """
    from keystone_tpu.ops import pallas_ops

    if carry is None:
        carry = sparse_gram_init(d, k, val_dtype)
    d_pad = carry[0].shape[0]

    def densify_chunk(cid):
        indices, values, Yc = chunk_fn(cid)
        c, w = indices.shape
        mask = (indices >= 0) & (indices < d)
        safe = jnp.where(mask, indices, 0).astype(jnp.int32)
        vals = jnp.where(mask, values, 0).astype(val_dtype)
        rows = jnp.broadcast_to(jnp.arange(c)[:, None], (c, w))
        dense = jnp.zeros((c, d_pad), val_dtype).at[rows, safe].add(vals)
        return dense, Yc

    # Fused-kernel eligibility is static (shapes only): probe the slab
    # shape abstractly so the decision never depends on a chunk id.
    slab_shape = jax.eval_shape(
        densify_chunk, jax.ShapeDtypeStruct((), jnp.asarray(cids).dtype)
    )[0]
    fused = use_pallas and pallas_ops.gram_corr_acc_ok(slab_shape)

    def fold_slab(G, AtY, yty, dense, Yc):
        if fused:
            G, AtY = pallas_ops.gram_corr_sym_acc(G, AtY, dense, Yc)
        else:
            if use_pallas and pallas_ops.gram_acc_ok(dense):
                G = pallas_ops.gram_sym_acc(G, dense)
            else:
                G = G + jax.lax.dot_general(
                    dense, dense, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            AtY = AtY + jax.lax.dot_general(
                dense, Yc.astype(dense.dtype), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        Yf = Yc.astype(jnp.float32)
        return G, AtY, yty + jnp.sum(Yf * Yf)

    cids = jnp.asarray(cids)
    num = int(cids.shape[0])
    if pipeline and num > 1:
        staged = densify_chunk(cids[0])

        def body(state, cid_next):
            (G, AtY, yty), (dense, Yc) = state
            nxt = densify_chunk(cid_next)  # independent of the fold below
            G, AtY, yty = fold_slab(G, AtY, yty, dense, Yc)
            return ((G, AtY, yty), nxt), None

        (carry, last), _ = jax.lax.scan(body, (carry, staged), cids[1:])
        carry = fold_slab(*carry, *last)
        return carry

    def body(carry, cid):
        dense, Yc = densify_chunk(cid)
        return fold_slab(*carry, dense, Yc), None

    carry, _ = jax.lax.scan(body, carry, cids)
    return carry


@functools.partial(jax.jit, static_argnames=("d",))
def _scatter_dense(indices, values, d: int):
    """Padded-COO -> dense scatter-add (module-level jit: one executable per
    (shape, d), reused across batches)."""
    n, width = indices.shape
    dense = jnp.zeros((n, d), dtype=values.dtype)
    safe_idx = jnp.where(indices >= 0, indices, 0)
    mask = (indices >= 0).astype(values.dtype)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, width))
    return dense.at[rows, safe_idx].add(values * mask)


@dataclass(frozen=True)
class Densify(Transformer):
    """Sparse batch -> dense batch (reference: Densify.scala:10-21)."""

    num_features: Optional[int] = None

    def apply(self, x):
        if isinstance(x, dict) and set(x.keys()) == {"indices", "values"}:
            d = self.num_features or int(np.max(x["indices"])) + 1
            out = np.zeros(d, dtype=np.float32)
            m = np.asarray(x["indices"]) >= 0
            out[np.asarray(x["indices"])[m]] = np.asarray(x["values"])[m]
            return jnp.asarray(out)
        return jnp.asarray(x)

    def batch_apply(self, data: Dataset) -> Dataset:
        return densify_dataset(data, self.num_features)


@dataclass(frozen=True)
class Sparsify(Transformer):
    """Dense batch -> padded-COO sparse batch (reference: Sparsify.scala:10-20)."""

    def apply(self, x):
        if isinstance(x, dict) and "indices" in x and "values" in x:
            return x  # already a sparse item: identity (mirrors Densify)
        x = np.asarray(x)
        idx = np.nonzero(x)[0]
        return {"indices": idx.astype(np.int32), "values": x[idx].astype(np.float32)}

    def batch_apply(self, data: Dataset) -> Dataset:
        if is_sparse_dataset(data):
            # Already padded-COO (e.g. the cost-model selector's
            # Sparsify->SparseLBFGS chain fitted on genuinely sparse
            # input): sparsifying is the identity.
            return data
        X = np.asarray(data.array)
        nnz_per_row = (X != 0).sum(axis=1)
        width = max(int(nnz_per_row.max()), 1)
        n = X.shape[0]
        indices = np.full((n, width), -1, dtype=np.int32)
        values = np.zeros((n, width), dtype=np.float32)
        for i in range(n):
            idx = np.nonzero(X[i])[0][:width]
            indices[i, : len(idx)] = idx
            values[i, : len(idx)] = X[i][idx]
        return Dataset({"indices": indices, "values": values}, n=data.n)


class SparseFeatureVectorizer(Transformer):
    """Map items to sparse vectors in a fixed feature space
    (reference: SparseFeatureVectorizer.scala:7-17)."""

    def __init__(self, feature_space: Dict[Any, int], max_nnz: Optional[int] = None):
        self.feature_space = feature_space
        self.num_features = len(feature_space)
        self.max_nnz = max_nnz

    @property
    def sparse_output_dim(self) -> int:
        """Declared output width — the cost-model sample collector threads
        this through as ``total_d`` so solver selection prices the true
        feature width instead of ``indices.max()+1`` over a tiny sample
        (which undershoots whenever the sample misses the top ids)."""
        space = self.feature_space.values()
        return (max(space) + 1) if space else 0

    def apply(self, item):
        pairs = sorted(
            (self.feature_space[f], v)
            for f, v in _item_pairs(item)
            if f in self.feature_space
        )
        idx = np.asarray([p[0] for p in pairs], dtype=np.int32)
        val = np.asarray([p[1] for p in pairs], dtype=np.float32)
        return {"indices": idx, "values": val}

    def batch_apply(self, data: Dataset) -> Dataset:
        return sparse_batch_from_items(
            data.to_list(), self.feature_space, self.max_nnz
        )

    def output_signature(self, sig):
        """Verifier declaration: weighted host items in, padded-COO
        sparse batch out (`sparse` kind — the dict pytree the sparse
        solvers consume)."""
        from keystone_tpu.workflow.verify import HostSig, expect_host

        sig = expect_host(sig, ("tf_dict", "ngram_counts"), self)
        return HostSig("sparse", n=sig.n, datum=sig.datum)


def _check_sparse_fit_input(est, input_sigs):
    """Shared fit-input contract for the sparse feature-space estimators:
    the DATA input must be weighted host items (a raw token stream here
    means the TermFrequency/weighting stage was dropped)."""
    from keystone_tpu.workflow.verify import HostSig, expect_host

    if input_sigs and isinstance(input_sigs[0], HostSig):
        expect_host(input_sigs[0], ("tf_dict", "ngram_counts"), est)


class CommonSparseFeatures(Estimator):
    """Keep the top-K features by document frequency, deterministic tie-break
    (reference: CommonSparseFeatures.scala:20-64)."""

    def __init__(self, num_features: int, max_nnz: Optional[int] = None):
        self.num_features = num_features
        self.max_nnz = max_nnz

    def fit(self, data: Dataset) -> SparseFeatureVectorizer:
        doc_freq: Counter = Counter()
        for i, item in enumerate(data.to_list()):
            for f, _ in _item_pairs(item):
                doc_freq[f] += 1
        # Deterministic: sort by (-count, repr) — the analog of the reference's
        # zipWithUniqueId tie-break.
        top = heapq.nsmallest(
            self.num_features, doc_freq.items(), key=lambda kv: (-kv[1], repr(kv[0]))
        )
        feature_space = {f: i for i, (f, _) in enumerate(top)}
        return SparseFeatureVectorizer(feature_space, self.max_nnz)

    def check_fit_signature(self, input_sigs):
        _check_sparse_fit_input(self, input_sigs)

    def fitted_signature(self, input_sigs):
        from keystone_tpu.workflow.verify import HostSig

        sig = input_sigs[0] if input_sigs else None
        n = getattr(sig, "n", None)
        datum = getattr(sig, "datum", False)
        return HostSig("sparse", n=n, datum=datum)


class AllSparseFeatures(Estimator):
    """Use every observed feature (reference: AllSparseFeatures.scala:15-27)."""

    def __init__(self, max_nnz: Optional[int] = None):
        self.max_nnz = max_nnz

    def fit(self, data: Dataset) -> SparseFeatureVectorizer:
        seen = {}
        for item in data.to_list():
            for f, _ in _item_pairs(item):
                if f not in seen:
                    seen[f] = len(seen)
        return SparseFeatureVectorizer(seen, self.max_nnz)

    def check_fit_signature(self, input_sigs):
        _check_sparse_fit_input(self, input_sigs)

    def fitted_signature(self, input_sigs):
        from keystone_tpu.workflow.verify import HostSig

        sig = input_sigs[0] if input_sigs else None
        n = getattr(sig, "n", None)
        datum = getattr(sig, "datum", False)
        return HostSig("sparse", n=n, datum=datum)
