"""Node library: featurizers, solvers, and plumbing nodes."""
