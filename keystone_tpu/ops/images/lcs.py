"""Local Color Statistics descriptors (reference:
nodes/images/LCSExtractor.scala:25-130; Clinchant et al. 2007).

Channel means/stds over subPatchSize boxes come from two box-filter
convolutions (image and image²); descriptors are then pure gathers at the
keypoint-neighborhood grid.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.utils import images as images_util
from keystone_tpu.utils.images import separable_conv2d_same
from keystone_tpu.workflow import Transformer


class LCSExtractor(Transformer):
    """Image -> (numNeighborhood²·channels·2, numKeypoints) matrix of local
    channel means and standard deviations (LCSExtractor.scala:49-129)."""

    def __init__(self, stride: int, stride_start: int, sub_patch_size: int):
        self.stride = stride
        self.stride_start = stride_start
        self.sub_patch_size = sub_patch_size
        # The outermost neighborhood offset is -2s + s//2 - 1; keypoints closer
        # than that to the border would wrap to the opposite image edge.
        min_start = 2 * sub_patch_size - sub_patch_size // 2 + 1
        if stride_start < min_start:
            raise ValueError(
                f"stride_start must be >= {min_start} for sub_patch_size="
                f"{sub_patch_size} so neighborhoods stay inside the image"
            )
        self._jit_features = jax.jit(self._features)

    def _features(self, image):
        X, Y, C = image.shape
        s = self.sub_patch_size
        box = np.full(s, 1.0 / s)

        means = separable_conv2d_same(image, box, box)  # (X, Y, C)
        sq = separable_conv2d_same(image * image, box, box)
        stds = jnp.sqrt(jnp.maximum(sq - means * means, 0.0))

        xs = np.arange(self.stride_start, X - self.stride_start, self.stride)
        ys = np.arange(self.stride_start, Y - self.stride_start, self.stride)

        # Neighborhood offsets (LCSExtractor.scala:63-69).
        start = -2 * s + s // 2 - 1
        end = s + s // 2 - 1
        offs = np.arange(start, end + 1, s)

        # For each channel c, neighbor (nx, ny): interleave mean, std
        # (LCSExtractor.scala:108-124).
        rows = []
        for c in range(C):
            for ox in offs:
                for oy in offs:
                    m = means[:, :, c][xs + ox, :][:, ys + oy]
                    sd = stds[:, :, c][xs + ox, :][:, ys + oy]
                    rows.append(m)
                    rows.append(sd)
        feats = jnp.stack(rows)  # (C·|offs|²·2, nx, ny)
        return feats.reshape(feats.shape[0], len(xs) * len(ys))

    def apply(self, image):
        image = images_util.as_float(image)
        if image.ndim == 2:
            image = image[:, :, None]
        return self._jit_features(image)

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            return data.map(self.apply)
        X = jnp.asarray(data.array, jnp.float32)
        out = jax.vmap(self._features)(X)
        return Dataset(out, n=data.n, mesh=data.mesh)
