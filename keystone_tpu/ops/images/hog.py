"""Felzenszwalb HOG features (reference: nodes/images/HogExtractor.scala:33-296,
itself a port of voc-release features.cc).

The reference walks pixels in nested while-loops; here the histogram binning
is a vectorized scatter-add and the block normalization is pure elementwise
work over the cell grid, all inside one jit per image shape.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.utils import images as images_util
from keystone_tpu.workflow import Transformer

_EPSILON = 0.0001

# Unit vectors for the 9 contrast-insensitive orientations
# (HogExtractor.scala:39-59).
_UU = np.array(
    [1.0, 0.9397, 0.7660, 0.5, 0.1736, -0.1736, -0.5, -0.7660, -0.9397],
    dtype=np.float64,
)
_VV = np.array(
    [0.0, 0.3420, 0.6428, 0.8660, 0.9848, 0.9848, 0.8660, 0.6428, 0.3420],
    dtype=np.float64,
)


@partial(jax.jit, static_argnames=("bin_size", "nx", "ny"))
def _hog(image, bin_size: int, nx: int, ny: int):
    X, Y, C = image.shape
    vis_x = min(nx * bin_size, X)
    vis_y = min(ny * bin_size, Y)

    # Gradients on interior visible pixels (HogExtractor.scala:85-113).
    img = image[:vis_x, :vis_y, :]
    dx = img[2:, 1:-1, :] - img[:-2, 1:-1, :]  # (vx-2, vy-2, C)
    dy = img[1:-1, 2:, :] - img[1:-1, :-2, :]
    mag_sq = dx * dx + dy * dy
    best_c = jnp.argmax(mag_sq, axis=-1)
    take = lambda a: jnp.take_along_axis(a, best_c[..., None], axis=-1)[..., 0]
    bdx, bdy = take(dx), take(dy)
    magnitude = jnp.sqrt(take(mag_sq))

    # Snap to one of 18 orientations (HogExtractor.scala:115-129). The
    # reference scans o = 0..8 checking dot then -dot with strict >, so ties
    # resolve to the earliest candidate in the order d0, -d0, d1, -d1, …
    # Interleaving preserves that order under argmax's first-wins ties
    # (e.g. vv[4] == vv[5] ties on pure-dx gradients).
    uu = jnp.asarray(_UU, dtype=image.dtype)
    vv = jnp.asarray(_VV, dtype=image.dtype)
    dots = uu[None, None, :] * bdy[..., None] + vv[None, None, :] * bdx[..., None]
    scan = jnp.stack([dots, -dots], axis=-1).reshape(dots.shape[:-1] + (18,))
    best_j = jnp.argmax(scan, axis=-1)
    best_o = (best_j >> 1) + 9 * (best_j & 1)

    # Bilinear binning into the cell grid (HogExtractor.scala:131-161).
    xs = jnp.arange(1, vis_x - 1, dtype=image.dtype)[:, None]
    ys = jnp.arange(1, vis_y - 1, dtype=image.dtype)[None, :]
    xp = (xs + 0.5) / bin_size - 0.5
    yp = (ys + 0.5) / bin_size - 0.5
    ixp = jnp.floor(xp).astype(jnp.int32)
    iyp = jnp.floor(yp).astype(jnp.int32)
    vx0 = xp - ixp
    vy0 = yp - iyp
    vx1 = 1.0 - vx0
    vy1 = 1.0 - vy0

    ixp = jnp.broadcast_to(ixp, magnitude.shape)
    iyp = jnp.broadcast_to(iyp, magnitude.shape)
    wx0 = jnp.broadcast_to(vx0, magnitude.shape)
    wy0 = jnp.broadcast_to(vy0, magnitude.shape)
    wx1 = jnp.broadcast_to(vx1, magnitude.shape)
    wy1 = jnp.broadcast_to(vy1, magnitude.shape)

    hist = jnp.zeros((nx, ny, 18), dtype=image.dtype)
    for cell_x, cell_y, w in (
        (ixp, iyp, wx1 * wy1),
        (ixp, iyp + 1, wx1 * wy0),
        (ixp + 1, iyp, wx0 * wy1),
        (ixp + 1, iyp + 1, wx0 * wy0),
    ):
        ok = (cell_x >= 0) & (cell_x < nx) & (cell_y >= 0) & (cell_y < ny)
        cx = jnp.where(ok, cell_x, 0)
        cy = jnp.where(ok, cell_y, 0)
        vals = jnp.where(ok, w * magnitude, 0.0)
        hist = hist.at[cx.ravel(), cy.ravel(), best_o.ravel()].add(vals.ravel())

    # Cell energies over opposite-orientation sums (HogExtractor.scala:168-196).
    folded = hist[:, :, :9] + hist[:, :, 9:]
    energy = jnp.sum(folded * folded, axis=-1)  # (nx, ny)

    nxf, nyf = max(nx - 2, 0), max(ny - 2, 0)
    if nxf == 0 or nyf == 0:
        return jnp.zeros((0, 32), dtype=image.dtype)

    # 2x2 block sums; the four normalizers per feature cell
    # (HogExtractor.scala:211-232).
    S = energy[:-1, :-1] + energy[1:, :-1] + energy[:-1, 1:] + energy[1:, 1:]
    n1 = 1.0 / jnp.sqrt(S[1:, 1:] + _EPSILON)  # block at (x+1, y+1)
    n2 = 1.0 / jnp.sqrt(S[:-1, 1:] + _EPSILON)  # (x, y+1)
    n3 = 1.0 / jnp.sqrt(S[1:, :-1] + _EPSILON)  # (x+1, y)
    n4 = 1.0 / jnp.sqrt(S[:-1, :-1] + _EPSILON)  # (x, y)

    h = hist[1:-1, 1:-1, :]  # (nxf, nyf, 18)
    hf = folded[1:-1, 1:-1, :]  # (nxf, nyf, 9)

    def clipped(hv, n):
        return jnp.minimum(hv * n[..., None], 0.2)

    c1, c2, c3, c4 = (clipped(h, n) for n in (n1, n2, n3, n4))
    sensitive = 0.5 * (c1 + c2 + c3 + c4)  # 18 features
    insensitive = 0.5 * sum(clipped(hf, n) for n in (n1, n2, n3, n4))  # 9
    texture = 0.2357 * jnp.stack(
        [jnp.sum(c, axis=-1) for c in (c1, c2, c3, c4)], axis=-1
    )  # 4
    trunc = jnp.zeros(sensitive.shape[:2] + (1,), dtype=image.dtype)

    feats = jnp.concatenate([sensitive, insensitive, texture, trunc], axis=-1)
    return feats.reshape(nxf * nyf, 32)


class HogExtractor(Transformer):
    """Image -> (numFeatureCells, 32) HOG feature matrix
    (reference: HogExtractor.scala:33-71)."""

    def __init__(self, bin_size: int):
        self.bin_size = bin_size

    def apply(self, image):
        image = images_util.as_float(image)
        # Java math.round = floor(x + 0.5) (HogExtractor.scala:64-65).
        nx = int(math.floor(image.shape[0] / self.bin_size + 0.5))
        ny = int(math.floor(image.shape[1] / self.bin_size + 0.5))
        return _hog(image, self.bin_size, nx, ny)

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            return data.map(self.apply)
        X = jnp.asarray(data.array, jnp.float32)
        nx = int(math.floor(X.shape[1] / self.bin_size + 0.5))
        ny = int(math.floor(X.shape[2] / self.bin_size + 0.5))
        out = jax.vmap(lambda im: _hog(im, self.bin_size, nx, ny))(X)
        return Dataset(out, n=data.n, mesh=data.mesh)
