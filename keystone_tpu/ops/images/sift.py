"""Dense multi-scale SIFT (the reference's native tier: vlfeat vl_dsift via
JNI — images/external/SIFTExtractor.scala:16-40, src/main/cpp/VLFeat.cxx:38-180).

TPU-native reformulation: per scale, orientation energy maps (8 planes) are
built from the smoothed gradient field, box-filtered (vl_dsift's flat-window
approximation) with XLA convs, and the 4×4 spatial bins are gathered at the
dense keypoint grid. Everything is static-shaped per (image shape, params),
so one jit covers the whole extractor; descriptors come back as the
reference's (128, numDescriptors) layout.

Parameters mirror the reference: per scale s, binSize_s = bin + 2s,
step_s = step + s·scaleStep, smoothing σ = binSize_s / 6 (magnif), flat
window, contrast threshold 0.005 zeroing, descriptors scaled to [0, 255]
shorts via min(512·v, 255).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.utils.images import gaussian_blur, to_grayscale
from keystone_tpu.workflow import Transformer

_NUM_ORIENTATIONS = 8
_MAGNIF = 6.0
_CONTRAST_THRESHOLD = 0.005


def _box_filter_same(img2d, size: int):
    """Same-size box sum filter along both axes (one XLA conv)."""
    ones = np.ones(size, dtype=np.float32)
    from keystone_tpu.utils.images import separable_conv2d_same

    return separable_conv2d_same(img2d[:, :, None], ones, ones)[:, :, 0]


def _scale_descriptors(image, bin_size: int, step: int):
    """Dense descriptors for one scale. image: (X, Y) grayscale in [0,1]."""
    X, Y = image.shape
    sigma = bin_size / _MAGNIF
    smoothed = gaussian_blur(image[:, :, None], sigma)[:, :, 0]

    dx = jnp.zeros_like(smoothed)
    dx = dx.at[1:-1, :].set((smoothed[2:, :] - smoothed[:-2, :]) * 0.5)
    dy = jnp.zeros_like(smoothed)
    dy = dy.at[:, 1:-1].set((smoothed[:, 2:] - smoothed[:, :-2]) * 0.5)
    mag = jnp.sqrt(dx * dx + dy * dy)
    angle = jnp.arctan2(dy, dx)  # [-pi, pi]

    # Linear orientation binning into the two adjacent of 8 bins.
    t = angle / (2 * math.pi) * _NUM_ORIENTATIONS  # [-4, 4]
    t = jnp.mod(t, _NUM_ORIENTATIONS)
    lo = jnp.floor(t)
    frac = t - lo
    lo_i = lo.astype(jnp.int32) % _NUM_ORIENTATIONS
    hi_i = (lo_i + 1) % _NUM_ORIENTATIONS
    planes = jnp.zeros((_NUM_ORIENTATIONS, X, Y), dtype=jnp.float32)
    xi, yi = jnp.meshgrid(jnp.arange(X), jnp.arange(Y), indexing="ij")
    planes = planes.at[lo_i, xi, yi].add(mag * (1.0 - frac))
    planes = planes.at[hi_i, xi, yi].add(mag * frac)

    # Flat-window spatial pooling: box sum of width binSize per bin.
    pooled = jax.vmap(lambda p: _box_filter_same(p, bin_size))(planes)

    # Keypoint grid: descriptor anchored at its top-left bin; the 4x4 bin
    # centers sit at anchor + i*bin + bin//2.
    extent = 3 * bin_size + bin_size // 2
    anchors_x = np.arange(0, X - extent, step)
    anchors_y = np.arange(0, Y - extent, step)
    if len(anchors_x) == 0 or len(anchors_y) == 0:
        return jnp.zeros((128, 0), dtype=jnp.float32)
    centers = np.arange(4) * bin_size + bin_size // 2

    gx = anchors_x[:, None] + centers[None, :]  # (nax, 4)
    gy = anchors_y[:, None] + centers[None, :]  # (nay, 4)
    # (8, nax, 4, nay, 4)
    vals = pooled[:, gx, :][:, :, :, gy]
    # Descriptor layout (bx, by, o) with o fastest -> 128 per keypoint.
    vals = jnp.transpose(vals, (1, 3, 2, 4, 0))  # (nax, nay, 4, 4, 8)
    desc = vals.reshape(len(anchors_x) * len(anchors_y), 128)

    # Normalize, clip at 0.2, renormalize; zero low-contrast descriptors.
    norm = jnp.sqrt(jnp.sum(desc * desc, axis=1, keepdims=True))
    d1 = desc / jnp.maximum(norm, 1e-12)
    d1 = jnp.minimum(d1, 0.2)
    norm2 = jnp.sqrt(jnp.sum(d1 * d1, axis=1, keepdims=True))
    d2 = d1 / jnp.maximum(norm2, 1e-12)
    # vl_dsift keypoint norm is the mean descriptor energy before normalization;
    # use the raw norm scaled by the pooled area as the contrast proxy.
    contrast_ok = norm > _CONTRAST_THRESHOLD
    d2 = jnp.where(contrast_ok, d2, 0.0)

    out = jnp.minimum(jnp.floor(512.0 * d2), 255.0)
    return out.T  # (128, n)


class SIFTExtractor(Transformer):
    """Image -> (128, numDescriptors) dense multi-scale SIFT matrix
    (reference: images/external/SIFTExtractor.scala:16-40)."""

    def __init__(self, step_size: int = 3, bin_size: int = 4, scales: int = 4, scale_step: int = 1):
        self.step_size = step_size
        self.bin_size = bin_size
        self.scales = scales
        self.scale_step = scale_step
        self.descriptor_size = 128
        self._jit_scales = [
            jax.jit(
                partial(
                    _scale_descriptors,
                    bin_size=bin_size + 2 * s,
                    step=step_size + s * scale_step,
                )
            )
            for s in range(scales)
        ]

    def apply(self, image):
        image = jnp.asarray(image, jnp.float32)
        if image.ndim == 3:
            image = to_grayscale(image)[:, :, 0]
        return jnp.concatenate([f(image) for f in self._jit_scales], axis=1)

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            return data.map(self.apply)
        # Device batches run the per-image jitted programs in a host loop
        # rather than one vmapped program: the vmapped multi-scale gather
        # program is ~7x slower to compile and respecializes on every batch
        # size, while the per-image programs compile once per image shape and
        # are reused across train/test/sample batches of any length (the
        # structural analog of the reference's per-image JNI calls inside RDD
        # maps, images/external/SIFTExtractor.scala:26-34).
        X = jnp.asarray(data.array, jnp.float32)
        if X.ndim == 4:
            X = jax.vmap(lambda im: to_grayscale(im)[:, :, 0])(X)
        outs = [self.apply(X[i]) for i in range(X.shape[0])]
        return Dataset(jnp.stack(outs), n=data.n, mesh=data.mesh)
