"""DAISY dense descriptors, Tola et al. (reference:
nodes/images/DaisyExtractor.scala:28-201).

The per-angle orientation maps and their cascaded Gaussian blurs are batched
XLA convolutions; ring sampling is a static set of gathers (Q·T offsets), so
the whole extractor jits into one program per image shape.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.utils import images as images_util
from keystone_tpu.utils.images import separable_conv2d_same
from keystone_tpu.workflow import Transformer

_FILTER1 = np.array([1.0, 0.0, -1.0])
_FILTER2 = np.array([1.0, 2.0, 1.0])


class DaisyExtractor(Transformer):
    """Image -> (H·(T·Q+1), numKeypoints) DAISY feature matrix
    (reference: DaisyExtractor.scala:28-201)."""

    def __init__(
        self,
        daisy_t: int = 8,
        daisy_q: int = 3,
        daisy_r: int = 7,
        daisy_h: int = 8,
        pixel_border: int = 16,
        stride: int = 4,
        patch_size: int = 24,
    ):
        self.T, self.Q, self.R, self.H = daisy_t, daisy_q, daisy_r, daisy_h
        self.pixel_border = pixel_border
        self.stride = stride
        self.patch_size = patch_size
        self.feature_threshold = 1e-8
        self.conv_threshold = 1e-6

        # Incremental blur kernels (DaisyExtractor.scala:49-64).
        sigma_sq = [(self.R * n / (2.0 * self.Q)) ** 2 for n in range(self.Q + 1)]
        diffs = [b - a for a, b in zip(sigma_sq, sigma_sq[1:])]
        self.g = []
        for t in diffs:
            rad = int(
                math.ceil(
                    math.sqrt(-2 * t * math.log(self.conv_threshold) - t * math.log(2 * math.pi * t))
                )
            )
            ns = np.arange(-rad, rad + 1, dtype=np.float64)
            self.g.append(np.exp(-(ns**2) / (2 * t)) / math.sqrt(2 * math.pi * t))

        # Ring sampling offsets per (level, angle)
        # (DaisyExtractor.scala:75-92: radius R(1+l)/Q, angle 2π(t-1)/T).
        self.offsets = np.zeros((self.Q, self.T, 2), dtype=np.int64)
        for l in range(self.Q):
            rad = self.R * (1.0 + l) / self.Q
            for t in range(self.T):
                # The reference evaluates 2π(angleCount−1)/T with angleCount
                # in [0, T) — the (t−1) offset is kept for parity
                # (DaisyExtractor.scala:82-88, 174).
                theta = 2 * math.pi * (t - 1) / self.T
                # Java math.round = floor(x + 0.5) (half-up), not Python's
                # banker's rounding (DaisyExtractor.scala:86-87).
                self.offsets[l, t, 0] = int(math.floor(rad * math.sin(theta) + 0.5))
                self.offsets[l, t, 1] = int(math.floor(rad * math.cos(theta) + 0.5))
        self._jit_features = jax.jit(self._features)

    def _normalize(self, h, axis):
        norm = jnp.sqrt(jnp.sum(h * h, axis=axis, keepdims=True))
        return jnp.where(norm > self.feature_threshold, h / norm, 0.0)

    def _features(self, image):
        image = image[:, :, :1]  # single-channel (reference uses channel 0)
        X, Y = image.shape[0], image.shape[1]
        ix = separable_conv2d_same(image, _FILTER1, _FILTER2)[:, :, 0]
        iy = separable_conv2d_same(image, _FILTER2, _FILTER1)[:, :, 0]

        # Orientation layers with cascaded blurs (DaisyExtractor.scala:113-135).
        angles = 2 * math.pi * np.arange(self.H) / self.H
        layers = []  # Q levels of (H, X, Y)
        level0 = []
        for a in angles:
            o = jnp.maximum(math.cos(a) * ix + math.sin(a) * iy, 0.0)
            level0.append(separable_conv2d_same(o, self.g[0], self.g[0])[:, :, 0])
        layers.append(jnp.stack(level0))
        for l in range(1, self.Q):
            prev = layers[-1]
            cur = [
                separable_conv2d_same(prev[h], self.g[l], self.g[l])[:, :, 0]
                for h in range(self.H)
            ]
            layers.append(jnp.stack(cur))

        xs = np.arange(self.pixel_border, X - self.pixel_border, self.stride)
        ys = np.arange(self.pixel_border, Y - self.pixel_border, self.stride)
        nx, ny = len(xs), len(ys)

        center = self._normalize(layers[0][:, xs, :][:, :, ys], axis=0)  # (H, nx, ny)

        # Column order: center, then angle-major/level-minor ring histograms
        # (DaisyExtractor.scala:155-186).
        blocks = [center]
        for t in range(self.T):
            for l in range(self.Q):
                ox, oy = int(self.offsets[l, t, 0]), int(self.offsets[l, t, 1])
                vals = layers[l][:, xs + ox, :][:, :, ys + oy]
                blocks.append(self._normalize(vals, axis=0))
        feats = jnp.concatenate(blocks, axis=0)  # (H(TQ+1), nx, ny)
        return feats.reshape(feats.shape[0], nx * ny)

    def apply(self, image):
        image = images_util.as_float(image)
        if image.ndim == 2:
            image = image[:, :, None]
        return self._jit_features(image)

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            return data.map(self.apply)
        X = jnp.asarray(data.array, jnp.float32)
        out = jax.vmap(self._features)(X)
        return Dataset(out, n=data.n, mesh=data.mesh)
