"""Convolution-family image nodes: Convolver, Pooler, Windower,
SymmetricRectifier.

The reference implements convolution as hand-rolled im2col into a reused
patch-matrix buffer followed by one BLAS-3 GEMM per image (reference:
nodes/images/Convolver.scala:128-220). Here the whole batch is one XLA
program: patch extraction (``lax.conv_general_dilated_patches``), per-patch
normalization, whitening-mean subtraction and the filter GEMM all fuse into a
single MXU-friendly computation over ``(n, x, y, c)`` arrays.

Layout note: the reference flattens patches/filters channel-fastest with its
second spatial axis slowest (Convolver.scala:152-190). We flatten row-major
over ``(x, y, c)`` — self-consistent between ``pack_filters`` and the patch
extractor, and the natural order for XLA.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from keystone_tpu.data import Dataset
from keystone_tpu.utils import images as image_utils
from keystone_tpu.workflow import Transformer


def _as_batch(x) -> tuple:
    """Return (batch array (n, X, Y, C), was_single)."""
    x = jnp.asarray(x)
    if x.ndim == 3:
        return x[None], True
    return x, False


def im2col(images, patch_size: int):
    """(n, X, Y, C) -> (n, X', Y', patch_size²·C) patches, flattened row-major
    over (px, py, c)."""
    n, X, Y, C = images.shape
    patches = lax.conv_general_dilated_patches(
        images,
        filter_shape=(patch_size, patch_size),
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    # Feature order from XLA is channel-slowest: (c, px, py). Reorder.
    xo, yo = X - patch_size + 1, Y - patch_size + 1
    patches = patches.reshape(n, xo, yo, C, patch_size, patch_size)
    patches = jnp.transpose(patches, (0, 1, 2, 4, 5, 3))
    return patches.reshape(n, xo, yo, patch_size * patch_size * C)


def normalize_patch_rows(patches, var_constant: float):
    """Per-patch mean/variance normalization, matching the reference's
    Stats.normalizeRows (utils/Stats.scala:112-123): subtract the mean, divide
    by sqrt(var + alpha) with the (d-1) variance denominator."""
    d = patches.shape[-1]
    mean = jnp.mean(patches, axis=-1, keepdims=True)
    centered = patches - mean
    var = jnp.sum(centered * centered, axis=-1, keepdims=True) / (d - 1.0)
    return centered / jnp.sqrt(var + var_constant)


class Convolver(Transformer):
    """Convolve images with a filter bank via im2col + one GEMM
    (reference: nodes/images/Convolver.scala:20-221).

    ``filters`` is ``(num_filters, patch_size²·channels)``, already whitened
    if a whitener is supplied (see :meth:`build`). Output image is
    ``(X-p+1, Y-p+1, num_filters)``.
    """

    def __init__(
        self,
        filters,
        img_x: int,
        img_y: int,
        img_channels: int,
        whitener=None,
        normalize_patches: bool = True,
        var_constant: float = 10.0,
    ):
        self.filters = jnp.asarray(filters, dtype=jnp.float32)
        self.img_x = img_x
        self.img_y = img_y
        self.img_channels = img_channels
        self.whitener = whitener
        self.normalize_patches = normalize_patches
        self.var_constant = var_constant
        self.patch_size = int(round((self.filters.shape[1] / img_channels) ** 0.5))

    @staticmethod
    def pack_filters(filter_images) -> jnp.ndarray:
        """(k, p, p, c) filter images -> (k, p·p·c) rows, row-major (x, y, c)
        (reference: Convolver.packFilters, Convolver.scala:99-125)."""
        f = jnp.asarray(filter_images, dtype=jnp.float32)
        return f.reshape(f.shape[0], -1)

    @classmethod
    def build(
        cls,
        filter_images,
        whitener=None,
        normalize_patches: bool = True,
        var_constant: float = 10.0,
        flip_filters: bool = False,
    ) -> "Convolver":
        """User-facing factory: takes unwhitened filter images ``(k, p, p, c)``
        and folds the whitening into the filter matrix
        (reference: Convolver.apply, Convolver.scala:60-89)."""
        f = jnp.asarray(filter_images, dtype=jnp.float32)
        if flip_filters:
            # MATLAB convnd parity: full x/y/channel reversal
            # (Convolver.scala:67-70 via ImageUtils.flipImage).
            f = jax.vmap(image_utils.flip_image)(f)
        packed = cls.pack_filters(f)
        if whitener is not None:
            packed = whitener.apply(packed) @ whitener.whitener.T
        k, p = f.shape[0], f.shape[1]
        c = f.shape[3]
        # img dims are supplied at apply time from the data; record patch shape.
        conv = cls(
            packed,
            img_x=-1,
            img_y=-1,
            img_channels=c,
            whitener=whitener,
            normalize_patches=normalize_patches,
            var_constant=var_constant,
        )
        conv.patch_size = p
        return conv

    def _convolve(self, images):
        # The declared compute dtype is float32 (declares_dtype_change):
        # narrow float64 loader output HERE, before any arithmetic, so the
        # eager apply() path and the compiled _batch_fn path agree — the
        # einsum's preferred_element_type alone would otherwise leave the
        # patch normalization running in f64 on the eager path.
        images = jnp.asarray(images, jnp.float32)
        from keystone_tpu.ops import pallas_images

        if pallas_images.conv_featurize_ok(images, self.filters):
            return pallas_images.conv_featurize(
                images,
                self.filters,
                self.whitener.means if self.whitener is not None else None,
                patch_size=self.patch_size,
                normalize_patches=self.normalize_patches,
                var_constant=self.var_constant,
            )
        patches = im2col(images, self.patch_size)
        if self.normalize_patches:
            patches = normalize_patch_rows(patches, self.var_constant)
        if self.whitener is not None:
            patches = patches - self.whitener.means
        return jnp.einsum(
            "nxyd,kd->nxyk", patches, self.filters,
            preferred_element_type=jnp.float32,
        )

    def apply(self, img):
        batch, single = _as_batch(img)
        out = self._convolve(batch)
        return out[0] if single else out

    # The convolution computes in float32 BY DESIGN (filters are cast at
    # construction, the einsum pins preferred_element_type): float64
    # image input narrowing to f32 here is the declared compute dtype,
    # not silent drift — tell the plan verifier so (workflow/verify.py).
    declares_dtype_change = True

    def _batch_fn(self, X):
        return self._convolve(jnp.asarray(X, jnp.float32))

    def device_fn(self):
        return self._batch_fn


class Pooler(Transformer):
    """Strided spatial pooling with a pixel function applied first
    (reference: nodes/images/Pooler.scala:21-69).

    Pool k covers ``[k·stride, k·stride + pool_size)`` in each spatial axis
    (the reference's strideStart = poolSize/2 with windows centered there),
    truncated at the image edge. ``pool_function`` is "sum" or "max".
    """

    def __init__(
        self,
        stride: int,
        pool_size: int,
        pixel_function: Optional[Callable] = None,
        pool_function: Union[str, Callable] = "sum",
    ):
        self.stride = stride
        self.pool_size = pool_size
        self.pixel_function = pixel_function
        if callable(pool_function):
            raise TypeError('pool_function must be "sum" or "max" (XLA reduce_window)')
        if pool_function not in ("sum", "max"):
            raise ValueError(f"unknown pool_function {pool_function}")
        self.pool_function = pool_function

    def _pool(self, images):
        n, X, Y, C = images.shape
        if self.pixel_function is not None:
            images = self.pixel_function(images)
        start = self.pool_size // 2
        npx = -(-(X - start) // self.stride)  # ceil
        npy = -(-(Y - start) // self.stride)
        ext_x = (npx - 1) * self.stride + self.pool_size
        ext_y = (npy - 1) * self.stride + self.pool_size
        pad_val = -jnp.inf if self.pool_function == "max" else 0.0
        images = jnp.pad(
            images,
            ((0, 0), (0, max(0, ext_x - X)), (0, max(0, ext_y - Y)), (0, 0)),
            constant_values=pad_val,
        )
        images = images[:, :ext_x, :ext_y, :]
        init, op = (
            (-jnp.inf, lax.max) if self.pool_function == "max" else (0.0, lax.add)
        )
        return lax.reduce_window(
            images,
            jnp.asarray(init, images.dtype),
            op,
            window_dimensions=(1, self.pool_size, self.pool_size, 1),
            window_strides=(1, self.stride, self.stride, 1),
            padding="VALID",
        )

    def apply(self, img):
        batch, single = _as_batch(img)
        out = self._pool(batch)
        return out[0] if single else out

    def _batch_fn(self, X):
        return self._pool(jnp.asarray(X, jnp.float32))

    def device_fn(self):
        return self._batch_fn


class Windower(Transformer):
    """Extract all stride-strided windows as separate images
    (reference: nodes/images/Windower.scala:13-56). A batch of n images
    becomes a batch of n·numWindows window images (RDD flatMap analog)."""

    def __init__(self, stride: int, window_size: int):
        self.stride = stride
        self.window_size = window_size

    def _windows(self, images):
        n, X, Y, C = images.shape
        w = self.window_size
        xs = np.arange(0, X - w + 1, self.stride)
        ys = np.arange(0, Y - w + 1, self.stride)
        rows = xs[:, None] + np.arange(w)[None, :]  # (nx, w)
        cols = ys[:, None] + np.arange(w)[None, :]  # (ny, w)
        out = images[:, rows, :, :]  # (n, nx, w, Y, C)
        out = out[:, :, :, cols, :]  # (n, nx, w, ny, w, C)
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))  # (n, nx, ny, w, w, C)
        return out.reshape(n, len(xs) * len(ys), w, w, C)

    def apply(self, img):
        batch, single = _as_batch(img)
        out = self._windows(batch)
        return out[0] if single else out.reshape((-1,) + out.shape[2:])

    def batch_apply(self, data: Dataset) -> Dataset:
        out = self._windows(jnp.asarray(data.array, jnp.float32)[: data.n])
        return Dataset(out.reshape((-1,) + out.shape[2:]))


class SymmetricRectifier(Transformer):
    """Two-sided ReLU doubling the channel count: channels c and c+C hold
    max(maxVal, x−α) and max(maxVal, −x−α)
    (reference: nodes/images/SymmetricRectifier.scala:7-32)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = max_val
        self.alpha = alpha

    def _rectify(self, x):
        pos = jnp.maximum(self.max_val, x - self.alpha)
        neg = jnp.maximum(self.max_val, -x - self.alpha)
        return jnp.concatenate([pos, neg], axis=-1)

    def apply(self, img):
        return self._rectify(jnp.asarray(img))

    def device_fn(self):
        return self._rectify
