from .conv import Convolver, Pooler, SymmetricRectifier, Windower
from .core import (
    CenterCornerPatcher,
    Cropper,
    GrayScaler,
    ImageExtractor,
    ImageVectorizer,
    LabeledImage,
    LabelExtractor,
    PixelScaler,
    RandomImageTransformer,
    RandomPatcher,
)
from .fisher import (
    FisherVector,
    GMMFisherVectorEstimator,
    ScalaGMMFisherVectorEstimator,
)
from .hog import HogExtractor
from .daisy import DaisyExtractor
from .lcs import LCSExtractor
from .sift import SIFTExtractor

__all__ = [
    "CenterCornerPatcher",
    "Convolver",
    "Cropper",
    "DaisyExtractor",
    "FisherVector",
    "GMMFisherVectorEstimator",
    "GrayScaler",
    "HogExtractor",
    "ImageExtractor",
    "ImageVectorizer",
    "LCSExtractor",
    "LabelExtractor",
    "LabeledImage",
    "PixelScaler",
    "Pooler",
    "RandomImageTransformer",
    "RandomPatcher",
    "SIFTExtractor",
    "ScalaGMMFisherVectorEstimator",
    "SymmetricRectifier",
    "Windower",
]
