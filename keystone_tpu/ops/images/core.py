"""Image plumbing nodes: scalers, croppers, patchers, vectorizer
(reference: nodes/images/{GrayScaler,PixelScaler,Cropper,ImageVectorizer,
RandomImageTransformer,CenterCornerPatcher,RandomPatcher,
LabeledImageExtractors}.scala)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.utils import images as image_utils
from keystone_tpu.workflow import Transformer


@dataclass
class LabeledImage:
    """An image with an integer label and optional filename
    (reference: utils/images/LabeledImage in ImageUtils.scala)."""

    image: Any
    label: int
    filename: str = ""


class ImageExtractor(Transformer):
    """LabeledImage -> image (reference: nodes/images/LabeledImageExtractors.scala)."""

    def apply(self, x: LabeledImage):
        return x.image


class LabelExtractor(Transformer):
    """LabeledImage -> label (reference: nodes/images/LabeledImageExtractors.scala)."""

    def apply(self, x: LabeledImage):
        return x.label


class GrayScaler(Transformer):
    """RGB -> luminance (reference: nodes/images/GrayScaler.scala)."""

    def apply(self, img):
        return image_utils.to_grayscale(img)

    def device_fn(self):
        return image_utils.to_grayscale


class PixelScaler(Transformer):
    """Rescale byte pixels to [0, 1) (reference: nodes/images/PixelScaler.scala)."""

    def apply(self, img):
        return jnp.asarray(img, jnp.float32) / 255.0

    def _batch_fn(self, X):
        return jnp.asarray(X, jnp.float32) / 255.0

    def device_fn(self):
        return self._batch_fn


class Cropper(Transformer):
    """Fixed-window crop (reference: nodes/images/Cropper.scala)."""

    def __init__(self, start_x: int, start_y: int, end_x: int, end_y: int):
        self.start_x, self.start_y = start_x, start_y
        self.end_x, self.end_y = end_x, end_y

    def apply(self, img):
        return image_utils.crop(img, self.start_x, self.start_y, self.end_x, self.end_y)

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            return data.map(self.apply)
        return data.map_batch(
            lambda X: X[:, self.start_x : self.end_x, self.start_y : self.end_y, :]
        )


class ImageVectorizer(Transformer):
    """Flatten an image to a vector, row-major over (x, y, c)
    (reference: nodes/images/ImageVectorizer.scala)."""

    def apply(self, img):
        return jnp.asarray(img).reshape(-1)

    def _batch_fn(self, X):
        return X.reshape(X.shape[0], -1)

    def device_fn(self):
        return self._batch_fn


class RandomImageTransformer(Transformer):
    """Apply a transform to each image with probability `chance`
    (reference: nodes/images/RandomImageTransformer.scala). The default
    transform is a horizontal flip; randomness is seeded explicitly."""

    def __init__(self, chance: float = 0.5, transform: Callable = None, seed: int = 0):
        self.chance = chance
        self.transform = transform or image_utils.flip_horizontal
        self._rng = np.random.default_rng(seed)

    def apply(self, img):
        if self._rng.random() < self.chance:
            return self.transform(img)
        return jnp.asarray(img)

    def batch_apply(self, data: Dataset) -> Dataset:
        X = jnp.asarray(data.array, jnp.float32)
        mask = jnp.asarray(self._rng.random(X.shape[0]) < self.chance)
        transformed = jax.vmap(self.transform)(X)
        out = jnp.where(mask[:, None, None, None], transformed, X)
        return Dataset(out, n=data.n, mesh=data.mesh)


class CenterCornerPatcher(Transformer):
    """Four corner patches + the center patch (optionally with horizontal
    flips): n images -> n·5 (or n·10) patches
    (reference: nodes/images/CenterCornerPatcher.scala:18-50)."""

    def __init__(self, patch_size_x: int, patch_size_y: int, horizontal_flips: bool = False):
        self.patch_size_x = patch_size_x
        self.patch_size_y = patch_size_y
        self.horizontal_flips = horizontal_flips

    def _patches(self, images):
        n, X, Y, C = images.shape
        px, py = self.patch_size_x, self.patch_size_y
        start_xs = [0, X - px, 0, X - px, (X - px) // 2]
        start_ys = [0, 0, Y - py, Y - py, (Y - py) // 2]
        out = []
        for sx, sy in zip(start_xs, start_ys):
            patch = images[:, sx : sx + px, sy : sy + py, :]
            out.append(patch)
            if self.horizontal_flips:
                out.append(patch[:, :, ::-1, :])
        stacked = jnp.stack(out, axis=1)  # (n, patches_per_image, px, py, C)
        return stacked

    def apply(self, img):
        img = jnp.asarray(img)
        return self._patches(img[None])[0]

    def batch_apply(self, data: Dataset) -> Dataset:
        X = jnp.asarray(data.array, jnp.float32)[: data.n]
        out = self._patches(X)
        return Dataset(out.reshape((-1,) + out.shape[2:]))

    @property
    def patches_per_image(self) -> int:
        return 10 if self.horizontal_flips else 5


class RandomPatcher(Transformer):
    """Uniformly random patches: n images -> n·num_patches patches
    (reference: nodes/images/RandomPatcher.scala:16-47)."""

    def __init__(self, num_patches: int, patch_size_x: int, patch_size_y: int, seed: int = 12334):
        self.num_patches = num_patches
        self.patch_size_x = patch_size_x
        self.patch_size_y = patch_size_y
        self.seed = seed

    def _patches(self, images):
        n, X, Y, C = images.shape
        px, py = self.patch_size_x, self.patch_size_y
        k = self.num_patches
        rng = np.random.default_rng(self.seed)
        sx = rng.integers(0, X - px + 1, size=(n, k))
        sy = rng.integers(0, Y - py + 1, size=(n, k))
        idx_n = np.arange(n)[:, None, None, None]
        rx = sx[:, :, None, None] + np.arange(px)[None, None, :, None]  # (n,k,px,1)
        ry = sy[:, :, None, None] + np.arange(py)[None, None, None, :]  # (n,k,1,py)
        return images[idx_n, rx, ry, :]  # (n, k, px, py, C)

    def apply(self, img):
        img = jnp.asarray(img)
        return self._patches(img[None])[0]

    def batch_apply(self, data: Dataset) -> Dataset:
        X = jnp.asarray(data.array, jnp.float32)[: data.n]
        out = self._patches(X)
        return Dataset(out.reshape((-1,) + out.shape[2:]))
