"""Fisher-vector encoding (reference: nodes/images/FisherVector.scala:17-94 and
the native enceval tier, src/main/cpp/EncEval.cxx:20-120).

The reference has two implementations — a Breeze one and a JNI C++
(enceval-toolkit) one picked by node-level optimization for k ≥ 32. On TPU
the encoding is three GEMMs plus elementwise work, so the *native* tier is a
single jit-compiled XLA program over the whole batch of descriptor matrices;
the per-item path serves ragged host-form data.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.learning.clustering import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
)
from keystone_tpu.workflow import Estimator, Transformer
from keystone_tpu.workflow.optimizable import OptimizableEstimator


@partial(jax.jit, static_argnames=())
def _fisher_encode(x, means, variances, weights, q):
    """Sanchez et al. FV from posteriors.

    x: (d, n) descriptors; q: (n, k) posteriors; means/variances: (d, k);
    weights: (k,). Returns (d, 2k) (FisherVector.scala:33-52).
    """
    n = x.shape[1]
    s0 = jnp.mean(q, axis=0)  # (k,)
    s1 = (x @ q) / n  # (d, k)
    s2 = ((x * x) @ q) / n  # (d, k)

    fv1 = (s1 - means * s0[None, :]) / (jnp.sqrt(variances) * jnp.sqrt(weights)[None, :])
    fv2 = (s2 - 2.0 * means * s1 + (means * means - variances) * s0[None, :]) / (
        variances * jnp.sqrt(2.0 * weights)[None, :]
    )
    return jnp.concatenate([fv1, fv2], axis=1)


class FisherVector(Transformer):
    """FV encoding of a (d, numDescriptors) matrix against a trained GMM
    (reference: FisherVector.scala:17-53). Output is (d, 2k)."""

    def __init__(self, gmm: GaussianMixtureModel):
        self.gmm = gmm

    def apply(self, x):
        x = jnp.asarray(x, jnp.float32)
        q = self.gmm.posteriors(x.T)  # (n, k) thresholded posteriors
        return _fisher_encode(
            x, self.gmm.means, self.gmm.variances, self.gmm.weights, q
        ).astype(jnp.float32)

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            return data.map(self.apply)
        X = jnp.asarray(data.array, jnp.float32)  # (b, d, n)

        def one(x):
            q = self.gmm.posteriors(x.T)
            return _fisher_encode(
                x, self.gmm.means, self.gmm.variances, self.gmm.weights, q
            ).astype(jnp.float32)

        return data.map_batch(lambda _: jax.vmap(one)(X))


class ScalaGMMFisherVectorEstimator(Estimator):
    """Fit a GMM treating every column of every input matrix as one training
    vector, then encode (reference: FisherVector.scala:60-73). The name keeps
    the reference's label; the implementation is the XLA path."""

    def __init__(self, k: int, gmm_seed: int = 0):
        self.k = k
        self.gmm_seed = gmm_seed

    def fit(self, data: Dataset) -> FisherVector:
        mats = data.to_list()
        cols = np.concatenate([np.asarray(m).T for m in mats], axis=0)  # (N, d)
        gmm = GaussianMixtureModelEstimator(self.k, seed=self.gmm_seed).fit_array(
            cols.astype(np.float64)
        )
        return FisherVector(gmm)


class GMMFisherVectorEstimator(OptimizableEstimator):
    """Optimizable FV estimator (reference: FisherVector.scala:85-94). The
    reference swaps to the native enceval JNI tier for k >= 32; both tiers
    here compile to the same fused XLA program, so optimize() keeps the
    single implementation."""

    def __init__(self, k: int, gmm_seed: int = 0):
        self.k = k
        self.gmm_seed = gmm_seed
        self._default = ScalaGMMFisherVectorEstimator(k, gmm_seed)

    @property
    def default(self) -> Estimator:
        return self._default

    def optimize(self, sample: Dataset) -> Optional[Estimator]:
        return self._default
