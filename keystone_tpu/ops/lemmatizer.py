"""English inflectional lemmatizer (the CoreNLP-fidelity tier).

The reference's CoreNLPFeatureExtractor lemmatizes tokens through Stanford
CoreNLP's finite-state Morpha stemmer (CoreNLPFeatureExtractor.scala:18).
CoreNLP is a JVM dependency that cannot be vendored here, so this module
implements the same *class* of analysis in-tree: inflectional morphology only
(noun number, verb tense/aspect/agreement, adjective comparison), via an
irregular-form exception table plus a Morpha/WordNet-morphy-style detachment
rule cascade with orthographic repair (consonant un-doubling, silent-e
restoration, y/i alternation). Derivational suffixes (-ness, -tion, -ly …)
are deliberately left intact — Morpha does not strip them either.

No POS input: like Morpha's bare mode, rules are tried noun-then-verb.
"""

from __future__ import annotations

from typing import Dict

_VOWELS = set("aeiou")

# Irregular inflected form -> lemma. Verbs (past/participle/3sg), nouns
# (plurals), adjectives (comparative/superlative). Curated for coverage of
# the most frequent English irregulars.
_IRREGULAR: Dict[str, str] = {
    # --- be / auxiliaries
    "am": "be", "is": "be", "are": "be", "was": "be", "were": "be",
    "been": "be", "being": "be",
    "has": "have", "had": "have", "having": "have",
    "does": "do", "did": "do", "done": "do", "doing": "do",
    "cannot": "can", "won't": "will", "n't": "not",
    # --- pasts of -ee verbs (the -eed rule keeps base forms unchanged)
    "agreed": "agree", "freed": "free", "decreed": "decree",
    "guaranteed": "guarantee", "refereed": "referee",
    # --- common irregular verbs (past, participle)
    "went": "go", "gone": "go", "goes": "go",
    "said": "say", "made": "make", "took": "take", "taken": "take",
    "came": "come", "saw": "see", "seen": "see", "got": "get",
    "gotten": "get", "knew": "know", "known": "know",
    "thought": "think", "gave": "give", "given": "give",
    "found": "find", "told": "tell", "became": "become",
    "left": "leave", "felt": "feel", "brought": "bring",
    "began": "begin", "begun": "begin", "kept": "keep", "held": "hold",
    "wrote": "write", "written": "write", "stood": "stand",
    "heard": "hear", "meant": "mean", "met": "meet", "ran": "run",
    "paid": "pay", "sat": "sit", "spoke": "speak", "spoken": "speak",
    "lay": "lie", "lain": "lie", "led": "lead", "grew": "grow",
    "grown": "grow", "lost": "lose", "fell": "fall", "fallen": "fall",
    "sent": "send", "built": "build", "understood": "understand",
    "drew": "draw", "drawn": "draw", "broke": "break", "broken": "break",
    "spent": "spend", "rose": "rise", "risen": "rise", "drove": "drive",
    "driven": "drive", "bought": "buy", "wore": "wear", "worn": "wear",
    "chose": "choose", "chosen": "choose", "ate": "eat", "eaten": "eat",
    "flew": "fly", "flown": "fly", "forgot": "forget",
    "forgotten": "forget", "spoilt": "spoil", "caught": "catch",
    "taught": "teach", "sought": "seek", "fought": "fight",
    "slept": "sleep", "swept": "sweep", "wept": "weep", "crept": "creep",
    "dealt": "deal", "dreamt": "dream", "burnt": "burn",
    "learnt": "learn", "lent": "lend", "bent": "bend", "shot": "shoot",
    "sold": "sell", "threw": "throw", "thrown": "throw", "shook": "shake",
    "shaken": "shake", "hid": "hide", "hidden": "hide", "bit": "bite",
    "bitten": "bite", "beat": "beat", "beaten": "beat",
    "sang": "sing", "sung": "sing", "sank": "sink", "sunk": "sink",
    "swam": "swim", "swum": "swim", "rang": "ring", "rung": "ring",
    "drank": "drink", "drunk": "drink", "sprang": "spring",
    "sprung": "spring", "stole": "steal", "stolen": "steal",
    "froze": "freeze", "frozen": "freeze", "woke": "wake",
    "woken": "wake", "tore": "tear", "torn": "tear", "swore": "swear",
    "sworn": "swear", "bore": "bear", "borne": "bear", "born": "bear",
    "laid": "lay", "slid": "slide", "struck": "strike", "hung": "hang",
    "stuck": "stick", "won": "win", "wound": "wind", "fed": "feed",
    "fled": "flee", "bled": "bleed", "bred": "breed", "sped": "speed",
    "dug": "dig", "spun": "spin", "lit": "light",
    "rode": "ride", "ridden": "ride",
    # --- invariant verbs whose surface looks inflected
    "cut": "cut", "put": "put", "set": "set", "let": "let", "hit": "hit",
    "cost": "cost", "hurt": "hurt", "shut": "shut", "spread": "spread",
    "read": "read",
    # --- irregular noun plurals
    "children": "child", "men": "man", "women": "woman", "feet": "foot",
    "teeth": "tooth", "geese": "goose", "mice": "mouse", "oxen": "ox",
    "people": "person", "lives": "life", "knives": "knife",
    "wives": "wife", "leaves": "leaf", "halves": "half",
    "selves": "self", "shelves": "shelf", "wolves": "wolf",
    "loaves": "loaf", "thieves": "thief", "calves": "calf",
    "scarves": "scarf", "indices": "index", "matrices": "matrix",
    "appendices": "appendix", "vertices": "vertex", "criteria": "criterion",
    "phenomena": "phenomenon", "data": "datum", "media": "medium",
    "analyses": "analysis", "theses": "thesis", "crises": "crisis",
    "hypotheses": "hypothesis", "bases": "basis", "diagnoses": "diagnosis",
    "oases": "oasis", "axes": "axis", "series": "series",
    "species": "species", "cacti": "cactus", "fungi": "fungus",
    "nuclei": "nucleus", "radii": "radius", "stimuli": "stimulus",
    "alumni": "alumnus", "syllabi": "syllabus",
    # --- invariant nouns
    "sheep": "sheep", "deer": "deer", "fish": "fish", "aircraft": "aircraft",
    # --- irregular adjectives
    "better": "good", "best": "good", "worse": "bad", "worst": "bad",
    "further": "far", "farther": "far", "furthest": "far",
    "farthest": "far", "less": "little", "least": "little",
    "more": "much", "most": "much", "elder": "old", "eldest": "old",
    # --- lexical pasts whose stem needs the e the rules can't infer
    "united": "unite", "cited": "cite", "invited": "invite",
    # --- -us nouns whose plural drops -es (vs "uses" -> "use")
    "buses": "bus", "viruses": "virus", "bonuses": "bonus",
    "campuses": "campus", "statuses": "status", "censuses": "census",
    # --- -as/-os singulars' plurals drop -es the same way
    "gases": "gas", "biases": "bias", "aliases": "alias",
    "atlases": "atlas", "canvases": "canvas",
}

# Surface forms that look inflected but are not (Morpha ships the same kind
# of exception list in its verbstem/noun tables): adverbs and nouns in -s,
# -ing nouns/prepositions, -ed-looking words.
_UNINFLECTED = frozenset({
    "always", "perhaps", "lens", "besides", "whereas", "alas", "thus",
    "morning", "evening", "during", "ceiling", "darling", "sibling",
    "something", "anything", "everything", "nothing",
    "hundred", "kindred", "sacred", "naked", "wicked", "rugged",
    "wretched", "beloved",
    # singular nouns in -as/-os/-ics the plural strip must not touch (found
    # by the idempotence property: bias -> "bia")
    "bias", "alias", "atlas", "canvas", "gas", "pancreas",
    "chaos", "cosmos", "ethos", "pathos", "mathematics", "physics",
})

# Words ending in "-ss"/"-us"/"-is" etc. that the -s rules must not touch.
_S_EXCEPTIONS = ("ss", "us", "is", "ous", "news")


def _vowel_groups(w: str) -> int:
    groups, in_group = 0, False
    for ch in w:
        if ch in _VOWELS or ch == "y":
            if not in_group:
                groups += 1
            in_group = True
        else:
            in_group = False
    return groups


def _undouble(stem: str) -> str:
    """stopp -> stop (but keep ll/ss/zz: tell, miss, buzz)."""
    if (
        len(stem) >= 3
        and stem[-1] == stem[-2]
        and stem[-1] not in _VOWELS
        and stem[-1] not in "lszf"
    ):
        return stem[:-1]
    return stem


def _restore_e(stem: str) -> str:
    """mak -> make: restore the silent e for single-syllable C-V-C stems
    (and cv-final stems like 'creat' whose last vowel group is shared)."""
    if len(stem) >= 2 and stem[-1] not in _VOWELS and stem[-1] not in "wxy":
        # Strict C-V-C: exactly one vowel LETTER before the final consonant
        # (vowel digraphs — look, seem, need, rain — take no silent e).
        single_vowel = stem[-2] in _VOWELS and (
            len(stem) < 3 or stem[-3] not in _VOWELS
        )
        if single_vowel and _vowel_groups(stem) == 1:
            return stem + "e"
    if stem.endswith(("at", "iz", "ys")) and _vowel_groups(stem) <= 2:
        return stem + "e"
    # C+"id" stems: decid-, provid-, divid-, resid- -> +e (vowel-"id" stems
    # like raid-/avoid- are real bases and keep their form).
    if (
        len(stem) >= 4
        and stem.endswith("id")
        and stem[-3] not in _VOWELS
    ):
        return stem + "e"
    if len(stem) >= 1 and stem[-1] in "uv":  # argu-, lov-, believ-, continu-
        return stem + "e"
    if len(stem) >= 2 and stem[-1] == "c" and stem[-2] in _VOWELS:
        return stem + "e"  # produc-, notic-
    return stem


def _strip_plural(w: str) -> str:
    if w.endswith("ies") and len(w) > 4:
        return w[:-3] + "y"  # studies -> study
    if w.endswith(("ches", "shes", "sses", "xes", "zes")):
        return w[:-2]  # watches, boxes
    if w.endswith("oes") and len(w) > 4:
        return w[:-2]  # potatoes -> potato (goes handled as irregular)
    if w.endswith("es") and not w.endswith(_S_EXCEPTIONS):
        # Ambiguous -es: "makes" -> "make" (stem keeps its e), "runs" has no
        # es. Try dropping only the "s" first: "makes" -> "make".
        return w[:-1]
    if w.endswith("s") and not w.endswith(_S_EXCEPTIONS) and len(w) > 3:
        return w[:-1]
    return w


def _strip_past(w: str) -> str:
    if w.endswith("ied") and len(w) > 4:
        return w[:-3] + "y"  # studied -> study
    if w.endswith("eed"):
        # Base forms (need, feed, speed, exceed) stay; pasts of -ee verbs
        # (agreed, freed, decreed) are in the irregular table.
        return w
    if w.endswith("ed") and len(w) > 3:
        stem = w[:-2]
        un = _undouble(stem)
        if un != stem:
            return un  # stopped -> stop
        return _restore_e(stem)  # loved: 'lov' -> 'love'; visited -> visit
    return w


def _strip_ing(w: str) -> str:
    if w.endswith("ing") and len(w) > 4:
        stem = w[:-3]
        if not any(c in _VOWELS or c == "y" for c in stem):
            return w  # "ring"-like: no vowel left, not an inflection
        if stem.endswith("y") and len(stem) >= 2:
            return stem  # studying -> study
        un = _undouble(stem)
        if un != stem:
            return un  # running -> run
        return _restore_e(stem)  # making -> make; visiting -> visit
    return w


def lemmatize(word: str) -> str:
    """Best-effort inflectional lemma of a lowercased token."""
    w = word.lower()
    # Irregulars first: "is"/"am" are two-letter words that must still map
    # to "be", so the table outranks the short-word guard.
    if w in _IRREGULAR:
        return _IRREGULAR[w]
    if w in _UNINFLECTED:
        return w
    if len(w) <= 2:
        return w
    if w.endswith("ing"):
        return _strip_ing(w)
    if w.endswith("ed"):
        return _strip_past(w)
    if w.endswith("s"):
        return _strip_plural(w)
    return w
