"""Plumbing nodes (reference: nodes/util/ — Cacher, VectorSplitter, label
indicators, classifiers, combiners, type casts).

Dense-array nodes are implemented as whole-batch jnp ops so XLA fuses them;
sparse-feature nodes live in :mod:`keystone_tpu.ops.nlp_sparse`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.workflow import Transformer


class FunctionNode:
    """A dataset-level function outside graph tracking
    (reference: pipelines/FunctionNode.scala:3)."""

    def apply(self, data):
        raise NotImplementedError

    def __call__(self, data):
        return self.apply(data)


@dataclass(frozen=True)
class Cacher(Transformer):
    """Materialize-and-hold passthrough (reference: nodes/util/Cacher.scala:15-25).

    On TPU this pins the dataset's buffers on device and marks the node's
    prefix as saveable so the optimizer can reuse the result across pipeline
    applications (the analog of RDD ``.cache()``).
    """

    name: Optional[str] = None

    # Verifier contract (workflow/verify.py): a cache marker is a
    # signature passthrough, and its PLACEMENT is checked — a cut that
    # severs an edge the fusion rules would compile into one program is
    # reported as `cache-splits-fusion`.
    is_cache = True

    def apply(self, x):
        return x

    def batch_apply(self, data: Dataset) -> Dataset:
        return data.cache()

    def output_signature(self, sig):
        return sig


@dataclass(frozen=True)
class ClassLabelIndicatorsFromIntLabels(Transformer):
    """Int label -> ±1 one-hot indicator vector
    (reference: nodes/util/ClassLabelIndicators.scala:15-38)."""

    num_classes: int

    def __post_init__(self):
        if self.num_classes < 2:
            raise ValueError("Must have at least two classes for ClassLabelIndicators")

    def apply(self, label: int):
        return self._encode(jnp.asarray(label))

    def _encode(self, labels):
        return 2.0 * jax.nn.one_hot(labels, self.num_classes, dtype=jnp.float32) - 1.0

    def batch_apply(self, data: Dataset) -> Dataset:
        labels = jnp.asarray(data.array).astype(jnp.int32)
        out = Dataset(self._encode(labels), n=data.n, mesh=data.mesh)
        # ±1 encoding is non-zero-preserving: re-zero padding rows.
        return out._rezero_padding()

    def output_signature(self, sig):
        """Verifier declaration: int labels (lead,) -> ±1 indicators
        (lead, num_classes) float32."""
        from keystone_tpu.workflow.verify import ArraySig, SignatureError

        if not isinstance(sig, ArraySig):
            return None
        if len(sig.shape) > (0 if sig.datum else 1):
            raise SignatureError(
                f"{self.label} expects scalar int labels per example, got "
                f"{sig.describe()}"
            )
        shape = (self.num_classes,) if sig.datum else (
            sig.shape[0], self.num_classes
        )
        return ArraySig(shape, "float32", n=sig.n, mesh=sig.mesh,
                        datum=sig.datum)


@dataclass(frozen=True)
class ClassLabelIndicatorsFromIntArrayLabels(Transformer):
    """Multi-label int array -> ±1 indicator vector
    (reference: nodes/util/ClassLabelIndicators.scala:40-55)."""

    num_classes: int
    valid_check: bool = True

    def __post_init__(self):
        if self.num_classes < 2:
            raise ValueError("Must have at least two classes for ClassLabelIndicators")

    def apply(self, labels):
        labels = np.atleast_1d(np.asarray(labels))
        if self.valid_check and (labels.min() < 0 or labels.max() >= self.num_classes):
            raise ValueError("Class labels out of range")
        out = -np.ones(self.num_classes, dtype=np.float32)
        out[labels] = 1.0
        return jnp.asarray(out)

    def batch_apply(self, data: Dataset) -> Dataset:
        return Dataset.of([self.apply(x) for x in data.to_list()])

    def output_signature(self, sig):
        from keystone_tpu.workflow.verify import ArraySig

        datum = getattr(sig, "datum", False)
        n = getattr(sig, "n", None)
        shape = (self.num_classes,) if datum else (n, self.num_classes)
        return ArraySig(shape, "float32", n=n, datum=datum)


@dataclass(frozen=True)
class MaxClassifier(Transformer):
    """argmax over scores -> int label (reference: nodes/util/MaxClassifier.scala:9-11)."""

    def apply(self, x):
        return jnp.argmax(x, axis=-1)

    def _batch_fn(self, X):
        return jnp.argmax(X, axis=-1)

    def device_fn(self):
        return self._batch_fn


@dataclass(frozen=True)
class TopKClassifier(Transformer):
    """Top-k score indices, descending; k clamps at the vector size
    (reference: nodes/util/TopKClassifier.scala:9-14 takes min(k, length))."""

    k: int

    def apply(self, x):
        x = jnp.asarray(x)
        _, idx = jax.lax.top_k(x, min(self.k, x.shape[-1]))
        return idx

    def batch_apply(self, data: Dataset) -> Dataset:
        arr = jnp.asarray(data.array)
        _, idx = jax.lax.top_k(arr, min(self.k, arr.shape[-1]))
        return Dataset(idx, n=data.n, mesh=data.mesh)

    def output_signature(self, sig):
        from keystone_tpu.workflow.verify import ArraySig, SignatureError

        if not isinstance(sig, ArraySig):
            return None
        if not sig.shape:
            raise SignatureError(
                f"{self.label} needs a score vector, got {sig.describe()}"
            )
        d = sig.shape[-1]
        k = min(self.k, d) if d is not None else self.k
        return ArraySig(sig.shape[:-1] + (k,), "int32", n=sig.n,
                        mesh=sig.mesh, datum=sig.datum)


@dataclass(frozen=True)
class VectorCombiner(Transformer):
    """Concatenate gathered branch vectors (reference: nodes/util/VectorCombiner.scala:10-14).

    Input items are tuples of vectors (the output of ``Pipeline.gather``);
    output is their concatenation.
    """

    def apply(self, x):
        return jnp.concatenate([jnp.asarray(v) for v in x], axis=-1)

    def batch_apply(self, data: Dataset) -> Dataset:
        if isinstance(data.data, tuple):
            out = jnp.concatenate([jnp.asarray(a) for a in data.data], axis=-1)
            return Dataset(out, n=data.n, mesh=data.mesh)
        return Dataset.of([self.apply(x) for x in data.to_list()])

    def device_combine_fn(self):
        """Gather-fusion contract: merge branch ARRAYS inside one program
        (workflow/fusion.py::GatherFusionRule)."""
        return lambda arrays: jnp.concatenate(
            [jnp.asarray(a) for a in arrays], axis=-1
        )


@dataclass(frozen=True)
class MatrixVectorizer(Transformer):
    """Flatten a matrix to a vector, column-major to match Breeze's
    ``DenseMatrix.toDenseVector`` (reference: nodes/util/MatrixVectorizer.scala:9-11)."""

    def apply(self, x):
        return jnp.asarray(x).T.reshape(-1)

    def _batch_fn(self, X):
        return jnp.transpose(X, (0, 2, 1)).reshape(X.shape[0], -1)

    def device_fn(self):
        return self._batch_fn


@dataclass(frozen=True)
class FloatToDouble(Transformer):
    """float32 -> float64 cast (reference: nodes/util/FloatToDouble.scala:9-11).

    On TPU float64 is emulated and slow; by default this widens to the
    framework's accumulation dtype (float32) and exists for API parity. Pass
    ``strict=True`` for true float64 (CPU meshes / x64-enabled tests).
    """

    strict: bool = False

    # The whole point of this node is a dtype change — tell the plan
    # verifier's drift check it is declared, not silent.
    declares_dtype_change = True

    def _dtype(self):
        return jnp.float64 if self.strict else jnp.float32

    def apply(self, x):
        return jnp.asarray(x, dtype=self._dtype())

    def _batch_fn(self, X):
        return jnp.asarray(X, dtype=self._dtype())

    def device_fn(self):
        return self._batch_fn


@dataclass(frozen=True)
class Shuffler(Transformer):
    """Random row permutation (the repartition/shuffle analog;
    reference: nodes/util/Shuffler.scala:14-22)."""

    seed: int = 0

    def apply(self, x):
        return x

    def output_signature(self, sig):
        return sig  # a permutation is a signature passthrough

    def batch_apply(self, data: Dataset) -> Dataset:
        if data.is_host:
            rng = np.random.default_rng(self.seed)
            items = data.to_list()
            return Dataset.of([items[i] for i in rng.permutation(len(items))])
        perm = jax.random.permutation(jax.random.key(self.seed), data.n)
        arr = data.array[: data.n][perm]
        out = Dataset(arr, n=data.n)
        return out.shard(data.mesh) if data.mesh is not None else out


class VectorSplitter(FunctionNode):
    """Split a (n, d) dataset into feature-axis blocks — the model-parallel
    partitioner (reference: nodes/util/VectorSplitter.scala:10-36).

    Returns a list of Datasets, each (n, block_size) (last may be smaller).
    On a 2-D mesh the blocks are what the block solvers iterate over; within a
    block, rows stay sharded over the ``data`` axis.
    """

    def __init__(self, block_size: int, num_features: Optional[int] = None):
        self.block_size = block_size
        self.num_features = num_features

    def apply(self, data: Dataset) -> List[Dataset]:
        arr = data.array
        d = self.num_features if self.num_features is not None else int(arr.shape[-1])
        blocks = []
        for start in range(0, d, self.block_size):
            stop = min(start + self.block_size, d)
            blocks.append(Dataset(arr[:, start:stop], n=data.n, mesh=data.mesh))
        return blocks

    def split_vector(self, vec):
        """Split a single vector into per-block vectors."""
        vec = jnp.asarray(vec)
        d = self.num_features if self.num_features is not None else int(vec.shape[-1])
        return [
            vec[start : min(start + self.block_size, d)]
            for start in range(0, d, self.block_size)
        ]
