"""Statistical featurization nodes (reference: nodes/stats/).

All dense nodes operate whole-batch on (n, d) arrays so XLA fuses the
elementwise work into surrounding GEMMs; per-item ``apply`` handles single
datums. Randomized nodes take explicit integer seeds (JAX PRNG keys derive
from them), replacing the reference's implicit global RNG draws.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.data import Dataset
from keystone_tpu.ops.util import FunctionNode
from keystone_tpu.workflow import Estimator, Transformer


# ---------------------------------------------------------------------------
# StandardScaler
# ---------------------------------------------------------------------------


class StandardScalerModel(Transformer):
    """Subtract column means (and optionally divide by stds)
    (reference: nodes/stats/StandardScaler.scala:16-32)."""

    def __init__(self, mean, std=None):
        self.mean = jnp.asarray(mean)
        self.std = None if std is None else jnp.asarray(std)

    def apply(self, x):
        out = jnp.asarray(x) - self.mean
        if self.std is not None:
            out = out / self.std
        return out

    def batch_apply(self, data: Dataset) -> Dataset:
        return data.map_batch(self.apply)


class StandardScaler(Estimator):
    """Column mean/std via a single sharded pass — sums compile to per-shard
    reductions + all-reduce, replacing treeAggregate(MultivariateOnlineSummarizer)
    (reference: nodes/stats/StandardScaler.scala:37-60)."""

    def __init__(self, normalize_std_dev: bool = True, eps: float = 1e-12):
        self.normalize_std_dev = normalize_std_dev
        self.eps = eps

    def fit(self, data: Dataset) -> StandardScalerModel:
        X = jnp.asarray(data.array)
        n = data.n
        # Padding rows are zero: sums are exact; divide by the true count.
        total = jnp.sum(X, axis=0)
        mean = total / n
        if not self.normalize_std_dev:
            return StandardScalerModel(mean)
        # Sample variance with the zero-padding correction:
        # sum((x - mean)^2) over real rows = sum(x^2) - n*mean^2.
        sumsq = jnp.sum(X * X, axis=0)
        var = (sumsq - n * mean * mean) / max(n - 1, 1)
        std = jnp.sqrt(jnp.maximum(var, 0.0))
        std = jnp.where(
            jnp.isnan(std) | jnp.isinf(std) | (jnp.abs(std) < self.eps), 1.0, std
        )
        return StandardScalerModel(mean, std)


# ---------------------------------------------------------------------------
# Random features
# ---------------------------------------------------------------------------


class CosineRandomFeaturesModel(Transformer):
    """x -> cos(x Wᵀ + b): Rahimi-Recht random features
    (reference: nodes/stats/CosineRandomFeatures.scala:19-45).

    The (num_out, num_in) projection is a single batch GEMM — the per-partition
    broadcast-W GEMM of the reference becomes one MXU matmul over the sharded
    batch with W replicated.
    """

    def __init__(self, W, b):
        self.W = jnp.asarray(W)
        self.b = jnp.asarray(b)
        if self.b.shape[0] != self.W.shape[0]:
            raise ValueError("# of rows in W and size of b should match")
        # (mesh, wrapped fn) — a fresh shard_map-of-lambda per call would
        # defeat jit's trace cache and recompile every batch.
        self._sharded_fused = None

    def apply(self, x):
        return jnp.cos(jnp.asarray(x) @ self.W.T + self.b)

    def _batch_fn(self, X):
        return jnp.cos(X @ self.W.T + self.b)

    def device_fn(self):
        """Stage-fusion contract (workflow/fusion.py): row-local cos-GEMM.

        The XLA form — inside a fused program XLA fuses the cosine into
        the matmul epilogue; the standalone batch path below still
        prefers the Pallas kernel, and fused STREAMED fits recover it via
        the bank extraction (streaming_ls._extract_bank)."""
        return self._batch_fn

    def batch_apply(self, data: Dataset) -> Dataset:
        import jax.tree_util as jtu
        from jax.sharding import PartitionSpec as P

        from keystone_tpu.ops import pallas_ops
        from keystone_tpu.parallel import mesh as mesh_lib

        mesh = data.mesh
        multi = mesh is not None and mesh_lib.axis_size(mesh, mesh_lib.DATA_AXIS) > 1
        if pallas_ops.pallas_enabled() and multi:
            # Row-sharded input: run the fused kernel per shard under
            # shard_map (W/b replicate into the body; no collective needed —
            # the featurization is embarrassingly row-parallel). The wrapper
            # is cached per mesh so repeat batches reuse the compiled program.
            if self._sharded_fused is None or self._sharded_fused[0] is not mesh:
                W, b = self.W, self.b
                self._sharded_fused = (
                    mesh,
                    mesh_lib.shard_map(
                        lambda X: pallas_ops.cosine_features(X, W, b),
                        mesh=mesh,
                        in_specs=P(mesh_lib.DATA_AXIS),
                        out_specs=P(mesh_lib.DATA_AXIS),
                        check_vma=False,  # pallas outputs carry no vma info
                    ),
                )
            return data.map_batch(self._sharded_fused[1])._rezero_padding()
        if pallas_ops.pallas_direct_ok(*jtu.tree_leaves(data.data)):
            # Fused Pallas matmul+cos: the pre-activation never hits HBM.
            return data.map_batch(
                lambda X: pallas_ops.cosine_features(X, self.W, self.b)
            )._rezero_padding()
        return data.map_batch(lambda X: jnp.cos(X @ self.W.T + self.b))._rezero_padding()


def CosineRandomFeatures(
    num_input_features: int,
    num_output_features: int,
    gamma: float,
    seed: int = 0,
    cauchy: bool = False,
) -> CosineRandomFeaturesModel:
    """Draw W ~ gaussian(·γ) (or cauchy(·γ)), b ~ U[0, 2π]
    (reference: CosineRandomFeatures.scala:50-61)."""
    kw, kb = jax.random.split(jax.random.key(seed))
    if cauchy:
        W = jax.random.cauchy(kw, (num_output_features, num_input_features)) * gamma
    else:
        W = jax.random.normal(kw, (num_output_features, num_input_features)) * gamma
    b = jax.random.uniform(kb, (num_output_features,)) * (2 * jnp.pi)
    return CosineRandomFeaturesModel(W, b)


def padded_pow2(n: int) -> int:
    """The FFT padding width every padded-FFT path shares: the next power
    of two ≥ n (minimum 2, so a width-1 input still has a non-trivial
    transform)."""
    return 1 << max(int(n - 1).bit_length(), 1)


def rfft_real_half(x, p: int, axis: int = -1):
    """Re(rfft(x))[bins 0..p/2) along ``axis`` — the shared epilogue of
    every padded-FFT path (``PaddedFFT`` single/batch, the packed
    gather's odd branch, and the SRHT sketch fold): the input is real
    and already padded to ``p``, and only the real parts of the first
    ``p // 2`` bins survive, so ``rfft`` computes the same DFT bins with
    half the butterfly work and a (p/2+1)-wide complex intermediate
    instead of p-wide. One implementation, so the bin convention (DC
    included, Nyquist dropped) cannot drift between callers — the
    batched-vs-single parity test in tests/test_learning_nodes.py pins
    it."""
    out = jnp.real(jnp.fft.rfft(x, axis=axis))
    return jax.lax.slice_in_dim(out, 0, p // 2, axis=axis)


def srht_chunk_sketch(dense_rows, signs, sample_bins, scale):
    """One block-SRHT fold step (Drineas et al., "Faster Least Squares
    Approximation"): sign-flip the chunk's rows, zero-pad the ROW axis to
    a power of two, mix with the real-FFT butterfly, keep Re of the
    first p/2 bins (:func:`rfft_real_half` — its fourth caller), and
    gather the chunk's sampled bins.

    ``dense_rows (c, d)``, ``signs (c,)`` ±1, ``sample_bins (m_c,)`` in
    ``[0, p//2)``; returns ``scale · (m_c, d)``. Stacking every chunk's
    sampled bins gives the block-diagonal SRHT ``S A`` of the whole row
    stream — each chunk is sketched independently, so the transform
    streams chunk-by-chunk and composes with the prefetch/resident
    tiers (``ops/learning/sketch.py``)."""
    c = dense_rows.shape[0]
    p = padded_pow2(c)
    Z = dense_rows * signs[:, None]
    Zp = jnp.pad(Z, ((0, p - c), (0, 0)))
    H = rfft_real_half(Zp, p, axis=0)  # (p//2, d)
    return scale * jnp.take(H, sample_bins, axis=0)


@dataclass(frozen=True)
class PaddedFFT(Transformer):
    """Zero-pad to the next power of two, FFT, keep the real parts of the first
    half (reference: nodes/stats/PaddedFFT.scala:13-21).

    The input is real, and only Re(bins 0..p/2) survive — the shared
    :func:`rfft_real_half` epilogue: at the MNIST bench geometry that
    halves both the FFT flops and the c64 round-trip bytes of the
    featurize phase (the HBM-bound piece of the row's roofline)."""

    def _padded_size(self, n: int) -> int:
        return padded_pow2(n)

    def apply(self, x):
        x = jnp.asarray(x)
        p = self._padded_size(x.shape[-1])
        padded = jnp.pad(x, [(0, p - x.shape[-1])])
        return rfft_real_half(padded, p)

    def _batch_fn(self, X):
        p = self._padded_size(X.shape[-1])
        padded = jnp.pad(X, [(0, 0), (0, p - X.shape[-1])])
        return rfft_real_half(padded, p)

    def device_fn(self):
        return self._batch_fn


def packed_fft_gather_fn(branches, combiner):
    """Recognize the MnistRandomFFT gather shape — every branch
    [RandomSignNode → PaddedFFT → LinearRectifier] over one input, merged
    by a VectorCombiner — and build the packed-pair batch program, or
    return None when the shape doesn't match (the caller falls back to
    per-branch composition).

    The per-branch composition reads X once PER BRANCH and runs nb real
    FFTs of width p. The packed program:

      - reads X once, applies the stacked sign flips as one broadcast
        multiply (the gather's input reads become one contiguous read);
      - packs branch pairs as real/imag of ONE width-p complex FFT —
        nb real transforms become ⌈nb/2⌉ complex ones — and unpacks
        Re(bins 0..p/2) by conjugate symmetry:

            Re A(k) = (Re Z(k) + Re Z((p−k) mod p)) / 2
            Re B(k) = (Im Z(k) + Im Z((p−k) mod p)) / 2

        (the scale-and-reversed-phase multiply of the classic two-real-
        FFTs-in-one-complex-FFT identity, folded into the FFT epilogue
        as two adds + one scale per bin);
      - applies the per-branch rectifiers and writes the concatenated
        output once, in the exact branch order the combiner produced.

    Branch members may arrive wrapped in a FusedBatchTransformer (stage
    fusion runs before gather fusion) — those are unwrapped by their
    ``members`` list.
    """
    from keystone_tpu.ops.util import VectorCombiner

    if not isinstance(combiner, VectorCombiner) or len(branches) < 2:
        return None
    flat = []
    for br in branches:
        members = []
        for m in br:
            sub = getattr(m, "members", None)
            members.extend(sub if sub is not None else [m])
        if len(members) != 3:
            return None
        sign, fft, rect = members
        if not (
            isinstance(sign, RandomSignNode)
            and isinstance(fft, PaddedFFT)
            and isinstance(rect, LinearRectifier)
        ):
            return None
        flat.append(members)
    widths = {int(m[0].signs.shape[0]) for m in flat}
    if len(widths) != 1:
        return None
    d_in = widths.pop()
    nb = len(flat)
    p = flat[0][1]._padded_size(d_in)
    signs = jnp.stack([m[0].signs for m in flat])  # (nb, d_in)
    alphas = jnp.asarray([float(m[2].alpha) for m in flat], jnp.float32)
    maxvals = jnp.asarray([float(m[2].max_val) for m in flat], jnp.float32)
    npairs = nb // 2

    def fused(X):
        n = X.shape[0]
        Z = X[:, None, :] * signs  # ONE read of X for all branches
        Zp = jnp.pad(Z, ((0, 0), (0, 0), (0, p - d_in)))
        outs = []
        if npairs:
            pairs = Zp[:, : 2 * npairs].reshape(n, npairs, 2, p)
            F = jnp.fft.fft(
                jax.lax.complex(pairs[:, :, 0], pairs[:, :, 1]), axis=-1
            )
            re, im = jnp.real(F), jnp.imag(F)

            def rev(a):  # a[..., (p − k) mod p]
                return jnp.roll(a[..., ::-1], 1, axis=-1)

            reA = (0.5 * (re + rev(re)))[..., : p // 2]
            reB = (0.5 * (im + rev(im)))[..., : p // 2]
            outs.append(
                jnp.stack([reA, reB], axis=2).reshape(n, 2 * npairs, p // 2)
            )
        if nb % 2:
            tail = rfft_real_half(Zp[:, -1], p)
            outs.append(tail[:, None, :])
        halves = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
        out = jnp.maximum(halves - alphas[None, :, None], maxvals[None, :, None])
        return out.reshape(n, nb * (p // 2))

    return fused


class RandomSignNode(Transformer):
    """Elementwise multiply by a fixed random ±1 vector
    (reference: nodes/stats/RandomSignNode.scala:11-24)."""

    def __init__(self, signs):
        self.signs = jnp.asarray(signs)

    @staticmethod
    def create(num_features: int, seed: int = 0) -> "RandomSignNode":
        signs = jax.random.rademacher(
            jax.random.key(seed), (num_features,), dtype=jnp.float32
        )
        return RandomSignNode(signs)

    def apply(self, x):
        return jnp.asarray(x) * self.signs

    def _batch_fn(self, X):
        return X * self.signs

    def device_fn(self):
        return self._batch_fn


# ---------------------------------------------------------------------------
# Elementwise stats nodes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LinearRectifier(Transformer):
    """max(maxVal, x - alpha) (reference: nodes/stats/LinearRectifier.scala:12-17)."""

    max_val: float = 0.0
    alpha: float = 0.0

    def apply(self, x):
        return jnp.maximum(jnp.asarray(x) - self.alpha, self.max_val)

    def _batch_fn(self, X):
        return jnp.maximum(X - self.alpha, self.max_val)

    def device_fn(self):
        return self._batch_fn


@dataclass(frozen=True)
class SignedHellingerMapper(Transformer):
    """sign(x)·√|x| (reference: nodes/stats/SignedHellingerMapper.scala:11-22)."""

    def apply(self, x):
        x = jnp.asarray(x)
        return jnp.sign(x) * jnp.sqrt(jnp.abs(x))

    def _batch_fn(self, X):
        return jnp.sign(X) * jnp.sqrt(jnp.abs(X))

    def device_fn(self):
        return self._batch_fn


@dataclass(frozen=True)
class NormalizeRows(Transformer):
    """Divide by L2 norm, eps-floored (reference: nodes/stats/NormalizeRows.scala:10-14)."""

    eps: float = 2.2e-16

    def apply(self, x):
        x = jnp.asarray(x)
        norm = jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), self.eps)
        return x / norm

    def device_fn(self):
        return self.apply


@dataclass(frozen=True)
class TermFrequency(Transformer):
    """Seq of items -> {item: weighting(count)} (host-side;
    reference: nodes/stats/TermFrequency.scala:18-20)."""

    weighting: Callable = field(default=lambda x: x)

    def apply(self, items):
        counts = {}
        for item in items:
            counts[item] = counts.get(item, 0) + 1
        return {k: self.weighting(v) for k, v in counts.items()}

    def batch_apply(self, data: Dataset) -> Dataset:
        return Dataset.of([self.apply(x) for x in data.to_list()])

    def output_signature(self, sig):
        """Verifier declaration (host op): item sequences in, feature→
        weight dicts out. A bare string input is rejected — counting its
        CHARACTERS as terms is virtually always a missing-Tokenizer bug."""
        from keystone_tpu.workflow.verify import HostSig, expect_host

        sig = expect_host(sig, ("tokens", "ngrams", "int_tokens"), self)
        return HostSig("tf_dict", n=sig.n, datum=sig.datum)


class ColumnSampler(Transformer):
    """Sample columns of per-item (d, cols) matrices
    (reference: nodes/stats/Sampling.scala:12-25)."""

    def __init__(self, num_samples: int, seed: int = 0):
        self.num_samples = num_samples
        self.seed = seed

    def apply(self, x):
        x = jnp.asarray(x)
        idx = jax.random.randint(
            jax.random.key(self.seed), (self.num_samples,), 0, x.shape[1]
        )
        return x[:, idx]


def sample_dataset(data: Dataset, num_items: int, seed: int = 0) -> Dataset:
    """Random row sample (the RDD.takeSample FunctionNode analog,
    reference: nodes/stats/Sampling.scala:27-32)."""
    k = min(num_items, data.n)
    if data.is_host:
        rng = np.random.default_rng(seed)
        items = data.to_list()
        idx = rng.choice(len(items), size=k, replace=False)
        return Dataset.of([items[i] for i in idx])
    idx = jax.random.choice(jax.random.key(seed), data.n, (k,), replace=False)
    return Dataset(jnp.asarray(data.array)[: data.n][idx], n=k)


class Sampler(FunctionNode):
    """Dataset-level row sampler (FunctionNode, operates outside graph
    tracking like the reference's — reference: nodes/stats/Sampling.scala:27-32)."""

    def __init__(self, size: int, seed: int = 0):
        self.size = size
        self.seed = seed

    def apply(self, data: Dataset) -> Dataset:
        return sample_dataset(data, self.size, self.seed)
